#!/usr/bin/env python
"""Benchmark harness (driver contract: print ONE JSON line to stdout).

Default mode measures the headline config of the reference — Allen-Cahn
Self-Adaptive PINN, N_f=50,000 collocation points, 2-128-128-128-128-1 tanh
MLP, per-point residual λ + per-point IC λ (reference ``examples/AC-SA.py``)
— as *training throughput in collocation-points/sec/chip*: full SA minimax
Adam steps (loss + grads over params and λ + dual Adam update) timed on the
default JAX backend.  The JSON line also carries ``flops_per_step`` (XLA cost
analysis of the compiled step) and ``mfu`` (achieved FLOP/s ÷ chip peak).

Resilience: the measurement runs in a SUBPROCESS with a hard timeout — this
host's TPU tunnel can hang or fail backend init (round-1 failure mode:
"Unable to initialize backend 'axon'", BENCH_r01.json rc=1; round-2 failure
mode: backend init hung for the full 1500 s worker budget and the driver
killed the run, BENCH_r02.json rc=124).  The supervisor therefore works to
a hard TOTAL wall budget (``BENCH_BUDGET`` env; default 1140 s for the
driver's no-flag invocation) and spends it in stages:

  1. PROBE — a tiny subprocess checks that the JAX backend initializes at
     all (<=120 s).  A hung tunnel costs 2 minutes here, not 25.
  2. LIVE — only if the probe saw a real accelerator: the measurement
     worker runs with the remaining budget.  A successful TPU payload is
     also persisted to ``BENCH_TPU_<mode>.json`` (same gate as
     scripts/_promote.sh) so future outages can still report hardware
     numbers.
  3. CACHED — probe/live failed: the last-good on-hardware payload is
     emitted IMMEDIATELY, tagged ``"backend_note": "tpu-cached-<date>"``,
     with a fresh small CPU sanity measurement attached when the budget
     allows (``cpu_sanity`` field).
  4. CPU fallback / total-failure sentinel — only when no hardware payload
     was ever captured.  Exit code is always 0; exactly one JSON line is
     the last stdout line in every path.

``vs_baseline`` is the ratio to a reference-style TensorFlow-2 train step
(same network, same residual via nested GradientTape, same dual-Adam SA
update, ``tf.function``-compiled) measured on the same host; the reference
framework has no TPU path — TF-on-this-host is what it can actually deliver
here.  If TF is unavailable, the last same-host TF measurement recorded in
``BENCH_BASELINE_CACHE.json`` is used; if neither exists, ``vs_baseline`` is
``null`` (never a fake 1.0).

Modes:
  (default)     SA train-step throughput + MFU
  --engines     generic vs fused-XLA vs fused-pallas residual engines
  --precision   float32(HIGHEST) vs bf16-matmul network forward config
  --scale       single-chip throughput sweep over N_f 50k..500k (500k is
                the reference's AC-dist-new.py multi-GPU config)
  --full        train AC-SA for real (Adam + L-BFGS) with periodic L2
                evaluation; reports wall-clock to rel-L2 <= 2.1e-2 (the
                SA-PINN paper figure cited at reference ``models.py:37``)
  --resample    adaptive-collocation race on Burgers: steps-to-rel-L2
                gate for fixed LHS vs adaptive (host path) vs adaptive +
                device-resident pipelined redraw, plus the per-redraw
                host-visible stall split
  --lint        not a measurement: the tdqlint static-analysis gate
                (tensordiffeq_tpu.analysis AST rules) over the package +
                bench.py — one verdict line, exit nonzero on findings
                (exempt from exit-0-always, like --slo)
  --slo TARGET  not a measurement: evaluate the default SLO set
                (telemetry.slo) against an existing runs/<dir> or a bench
                payload JSON file, print one machine-readable verdict
                line, and exit nonzero on breach — the CI gate over
                captured evidence (the one mode exempt from the
                always-exit-0 contract, by design)

Env knobs: ``BENCH_NF`` (default 50000), ``BENCH_STEPS`` (default 100),
``BENCH_FAST=1`` (tiny smoke config), ``BENCH_TIMEOUT`` (per-attempt
subprocess seconds).
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE = os.path.join(REPO, "BENCH_BASELINE_CACHE.json")
# Directory holding BENCH_TPU_<mode>.json last-good hardware payloads
# (module-level so in-process tests can point it at a tmp dir; the env var
# does the same for subprocess tests, which must not read the repo's live
# cached TPU payloads).
TPU_CACHE_DIR = os.environ.get("BENCH_TPU_CACHE_DIR", REPO)
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
# Wall-clock reserved for the cached-emit path after a live attempt fails.
RESERVE_S = 45

EPS = 0.0001  # Allen-Cahn diffusion coefficient


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------- #
# JAX (ours)
# --------------------------------------------------------------------------- #
_UNSET = object()

# engine-artifact names -> CollocationSolverND.compile(fused=...) values
# ("fused-minimax" maps to fused=True: the minimax loss engine auto-adopts
# on top of any fused residual engine — compile(minimax=None) default)
_ENGINE_MAP = {"pallas": "pallas", "fused-pallas": "pallas",
               "fused": True, "fused-xla": True, "fused-minimax": True,
               "generic": False, "autotune": "autotune"}


def engine_hint(default="autotune"):
    """Residual-engine choice for timed runs on TPU: ``BENCH_ENGINE`` env
    wins, else the measured-best engine recorded in the last promoted
    ``BENCH_TPU_engines.json``, else autotune.

    Skipping autotune cuts the first-compile count ~4x (autotune compiles
    generic + fused + several pallas tile candidates, each with its numeric
    cross-check).  On a slow tunnel that is the difference between a live
    measurement and a supervisor timeout: a healthy 20 s probe window does
    not guarantee 25 minutes of compile service (round-3 step-1 lesson).
    Only consulted when the backend is TPU — the artifact is a TPU
    measurement, and pallas interpret mode must never win a CPU run.  The
    hinted engine still runs its numeric cross-check at compile time, and
    callers fall back to autotune if it fails to build."""
    env = os.environ.get("BENCH_ENGINE")
    if env:
        if env not in _ENGINE_MAP:
            log(f"[engine] unknown BENCH_ENGINE={env!r} (valid: "
                f"{sorted(_ENGINE_MAP)}); using {default!r}")
        return _ENGINE_MAP.get(env, default)
    import jax
    if jax.default_backend() != "tpu":
        return default
    try:
        with open(tpu_cache_file(["--engines"])) as fh:
            engines = json.load(fh).get("engines", {})
        ok = {k: v for k, v in engines.items() if isinstance(v, (int, float))}
        best = max(ok, key=ok.get)
        hint = _ENGINE_MAP.get(best, default)
        log(f"[engine] using measured-best engine {best!r} -> fused={hint!r}"
            f" (set BENCH_ENGINE=autotune to re-tune)")
        return hint
    except Exception:
        return default


def precision_hint():
    """``(fused, fused_dtype, minimax)`` for the headline run, from the
    promoted ``BENCH_TPU_precision.json``: when a mixed-precision fused
    config (bf16 matmul operands, f32 accumulation) is the measured-best
    on chip, the default-mode throughput adopts it — the PERF.md roofline
    identifies removing the six-pass f32 multiplier as THE lever past
    ~9% MFU, and bf16 SA training is accuracy-validated end-to-end
    (``runs/bf16_accuracy.json``, CONVERGENCE.md).  The full-precision
    net-dtype config (``bf16-matmul``) is never hinted — measured to FAIL
    end-to-end accuracy (rel-L2 3.7x worse than f32 at equal budget,
    ``runs/bf16_net_accuracy.json``): only the fused
    engines carry the end-to-end accuracy evidence.  The ``minimax``
    element pins the loss-engine flavor the winning row was MEASURED
    with (the bf16-taylor/bf16-pallas rows run ``minimax=False``,
    bf16-minimax runs the fused minimax step) so the replayed headline
    config is the measured one, not a different auto-adopted engine.
    ``BENCH_DTYPE=f32`` disables the hint, and an explicit
    ``BENCH_ENGINE`` override wins outright (engine_hint's contract) —
    no dtype hint rides along with it.  Returns ``(None, None, None)``
    when no hint applies."""
    if os.environ.get("BENCH_DTYPE", "").lower() in ("off", "f32",
                                                     "float32"):
        return None, None, None
    if os.environ.get("BENCH_ENGINE"):
        return None, None, None
    import jax
    if jax.default_backend() != "tpu":
        return None, None, None
    try:
        # load_cached_tpu applies the artifact-safety guards (last JSON
        # line, backend=="tpu", no sentinel backend_note) — same reader
        # every other artifact consumer uses
        payload = load_cached_tpu(["--precision"])
        info = (payload or {}).get("precision", {})
        ok = {k: v["pts_per_sec"] for k, v in info.items()
              if isinstance(v, dict)
              and isinstance(v.get("pts_per_sec"), (int, float))}
        # pick the best of the VALIDATED configs, not the overall sweep
        # winner: on 2026-08-01 the unvalidated full-bf16-net row edged
        # out bf16-pallas by 6% and the old `best == ...` chain returned
        # no hint at all, leaving the headline on f32-pallas at HALF the
        # validated mixed-precision throughput
        validated = {k: ok[k] for k in ("bf16-pallas", "bf16-taylor",
                                        "bf16-minimax")
                     if k in ok}
        if not validated:
            return None, None, None
        best = max(validated, key=validated.get)
        # only adopt when it actually beats the f32 rows from the same sweep
        f32_best = max((v for k, v in ok.items() if k.startswith("f32")),
                       default=None)
        if f32_best is not None and validated[best] <= f32_best:
            return None, None, None
        # the minimax element replays the loss engine the row MEASURED:
        # bf16-taylor/bf16-pallas ran minimax=False, bf16-minimax=True
        hint = (("pallas", "bfloat16", False) if best == "bf16-pallas"
                else (True, "bfloat16", True) if best == "bf16-minimax"
                else (True, "bfloat16", False))
        log(f"[precision] measured-best config {best!r} -> "
            f"fused={hint[0]!r}, fused_dtype={hint[1]!r}, "
            f"minimax={hint[2]!r} (set BENCH_DTYPE=f32 to disable)")
        return hint
    except Exception:
        return None, None, None


def build_solver(n_f, nx, nt, widths, seed=0, fused=None, dtype=_UNSET,
                 precision=_UNSET, fused_dtype=None, remat=False,
                 minimax=None):
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import IC, CollocationSolverND, DomainND, grad, periodicBC

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], nx)
    domain.add("t", [0.0, 1.0], nt)
    domain.generate_collocation_points(n_f, seed=seed)

    def func_ic(x):
        return x ** 2 * np.cos(np.pi * x)

    def deriv_model(u, x, t):
        return u(x, t), grad(u, "x")(x, t)

    bcs = [IC(domain, [func_ic], var=[["x"]]),
           periodicBC(domain, ["x"], [deriv_model])]

    def f_model(u, x, t):
        u_xx = grad(grad(u, "x"), "x")
        u_t = grad(u, "t")
        uv = u(x, t)
        return u_t(x, t) - EPS * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv

    network = None
    if dtype is not _UNSET or precision is not _UNSET:
        import jax.numpy as jnp
        from tensordiffeq_tpu.networks import neural_net
        kw = {}
        if dtype is not _UNSET:
            kw["dtype"] = jnp.dtype(dtype).type
        if precision is not _UNSET:
            kw["precision"] = precision
        network = neural_net([2, *widths, 1], **kw)

    rng = np.random.RandomState(seed)
    solver = CollocationSolverND(verbose=False)
    solver.compile(
        [2, *widths, 1], f_model, domain, bcs, Adaptive_type=1,
        dict_adaptive={"residual": [True], "BCs": [True, False]},
        init_weights={"residual": [rng.rand(n_f, 1)],
                      "BCs": [100.0 * rng.rand(nx, 1), None]},
        fused=fused, network=network, fused_dtype=fused_dtype, remat=remat,
        minimax=minimax)
    return solver


def build_system_solver(n_f, nx, nt, widths, seed=0, minimax=None):
    """A coupled 2-equation Schrödinger-type system (the classical
    2-output PINN benchmark shape) at the bench domain sizes, with
    per-point SA λ on BOTH residual channels — the multi-component arm of
    ``--mode minimax``: it exercises the widened ``[N, E]`` fused unit
    (one λ/weight channel per equation) end to end."""
    from tensordiffeq_tpu import CollocationSolverND, DomainND, IC, grad, periodicBC

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], nx)
    domain.add("t", [0.0, 1.0], nt)
    domain.generate_collocation_points(n_f, seed=seed)

    ics = IC(domain,
             [lambda x: x ** 2 * np.cos(np.pi * x), lambda x: 0.0 * x],
             var=[["x"], ["x"]])

    def deriv_model(u, x, t):
        return (u[0](x, t), u[1](x, t),
                grad(u[0], "x")(x, t), grad(u[1], "x")(x, t))

    bcs = [ics, periodicBC(domain, ["x"], [deriv_model])]

    def f_model(u, x, t):
        uv, vv = u[0](x, t), u[1](x, t)
        sq = uv ** 2 + vv ** 2
        f_u = grad(u[0], "t")(x, t) \
            + 0.5 * grad(grad(u[1], "x"), "x")(x, t) + sq * vv
        f_v = grad(u[1], "t")(x, t) \
            - 0.5 * grad(grad(u[0], "x"), "x")(x, t) - sq * uv
        return f_u, f_v

    rng = np.random.RandomState(seed)
    solver = CollocationSolverND(verbose=False)
    solver.compile(
        [2, *widths, 2], f_model, domain, bcs, Adaptive_type=1,
        dict_adaptive={"residual": [True, True], "BCs": [True, False]},
        init_weights={"residual": [rng.rand(n_f, 1), rng.rand(n_f, 1)],
                      "BCs": [100.0 * rng.rand(nx, 1), None]},
        fused=True, minimax=minimax)
    return solver


def make_sa_step(solver):
    import jax
    import optax
    from tensordiffeq_tpu.training.fit import make_optimizer

    opt = make_optimizer()

    def train_step(trainables, opt_state, X):
        def loss_over(tr):
            return solver.loss_fn(tr["params"], tr["lambdas"]["BCs"],
                                  tr["lambdas"]["residual"], X)
        (total, _), grads = jax.value_and_grad(loss_over, has_aux=True)(trainables)
        updates, opt_state = opt.update(grads, opt_state, trainables)
        return optax.apply_updates(trainables, updates), opt_state, total

    trainables = {"params": solver.params, "lambdas": solver.lambdas}
    opt_state = opt.init(trainables)
    return train_step, trainables, opt_state


def compiled_flops(compiled):
    """FLOPs per step from the compiled executable's XLA cost model (None
    if the backend doesn't expose it) — single-sourced in
    :mod:`tensordiffeq_tpu.telemetry.costmodel` since PR 7; the fit- and
    serve-time live gauges quote the same read."""
    from tensordiffeq_tpu.telemetry import costmodel
    flops = costmodel.compiled_flops(compiled)
    if flops is None:
        log("[mfu] cost_analysis unavailable for this program/backend")
    return flops


def _record_step_split(n_steps, dispatch_s, device_s):
    """Record the fenced dispatch/device per-step split of a timed loop in
    the shared telemetry registry (phase=bench), so every mode's payload
    can embed a step-time breakdown (see bench_telemetry_block)."""
    try:
        from tensordiffeq_tpu import telemetry
    except Exception:
        return
    scope = telemetry.default_registry().scope(phase="bench")
    n = max(int(n_steps), 1)
    scope.histogram("step_time_dispatch_s").observe(dispatch_s / n)
    scope.histogram("step_time_device_s").observe(device_s / n)


def bench_telemetry_block():
    """The ``telemetry`` block embedded in every live worker payload:
    step-time breakdown (phase=bench loops and, under --full, the
    trainer's adam/l-bfgs phases), device memory peak, and the full
    shared-registry snapshot (serving compile/pad-waste/queue metrics in
    --serving mode)."""
    from tensordiffeq_tpu import profiling, telemetry
    reg = telemetry.default_registry().as_dict()
    peak = profiling.device_memory_peak()
    step = {k: v for k, v in reg.get("histograms", {}).items()
            if k.startswith("step_time")}
    return {"memory_peak_bytes": peak, "step_time": step, "metrics": reg}


def _analytic_step_floor(n_f, widths):
    """Lower bound on model FLOPs for one SA train step (see
    :func:`tensordiffeq_tpu.telemetry.costmodel.analytic_step_floor`, the
    single source since PR 7).  A compiled-step count below this is
    physically impossible — it means XLA's cost model could not see into
    a custom call (pallas kernels score 0, so a pallas-engine step
    reports only its non-kernel scraps: the 2026-08-01 default capture
    said 0.48 GFLOP for a step the roofline puts at ~93 GFLOP, and
    quoted MFU 0.0004)."""
    from tensordiffeq_tpu.telemetry import costmodel
    return costmodel.analytic_step_floor(n_f, [2, *widths, 1])


def aot_compile_sa_step(solver):
    """``(step, trainables, opt_state)`` — the jitted SA train step AOT
    compiled at the solver's real shapes.  ONE compile serves both the
    cost analysis and the timed loop; shared by every bench path so the
    donation policy and argument order can never drift apart between the
    throughput, precision, and flop-basis compiles."""
    import jax
    train_step, trainables, opt_state = make_sa_step(solver)
    step = jax.jit(train_step, donate_argnums=(0, 1)) \
        .lower(trainables, opt_state, solver.X_f).compile()
    return step, trainables, opt_state


_GENERIC_FLOPS: dict = {}


def generic_step_flops(n_f, nx, nt, widths):
    """``(flops, basis_label)`` — fallback FLOPs basis from the generic
    autodiff engine's compiled step: the same mathematical step with every
    FLOP visible to the cost model (XLA counts logical flops, not MXU
    passes: f32-HIGHEST / f32-default / bf16-matmul all compile to the
    same ~92.7 GFLOP at the flagship config)."""
    key = (n_f, nx, nt, tuple(widths))
    if key in _GENERIC_FLOPS:
        return _GENERIC_FLOPS[key], "generic-engine"
    # a same-shape basis at another N_f scales linearly to this one (the
    # residual term — linear in the collocation batch — dominates; the
    # n_f-independent BC terms put the error well under 1% across the
    # --scale sweep's 50k->500k range).  This keeps a pallas-engine scale
    # sweep at ONE basis compile instead of one whole-program compile per
    # sweep point inside the worker's timeout budget.
    for (kn, knx, knt, kw), v in _GENERIC_FLOPS.items():
        if (knx, knt, kw) == (nx, nt, tuple(widths)) and v is not None:
            return v * n_f / kn, "generic-engine-scaled"
    try:
        t0 = time.time()
        solver = build_solver(n_f, nx, nt, widths, fused=False)
        step, _, _ = aot_compile_sa_step(solver)
        flops = compiled_flops(step)
        log(f"[mfu] generic-engine flop basis N_f={n_f}: "
            f"{flops} ({time.time() - t0:.1f}s)")
        # a None from compiled_flops is deterministic (cost analysis not
        # exposed by this backend) — cache it so later rows don't rebuild
        # and recompile for the same answer.  Exceptions (e.g. transient
        # RESOURCE_EXHAUSTED while the measured step's donated buffers
        # still hold HBM) are NOT cached: a later attempt may succeed.
        _GENERIC_FLOPS[key] = flops
        return flops, ("generic-engine" if flops is not None else None)
    except Exception as e:
        log(f"[mfu] generic flop basis unavailable this attempt "
            f"({type(e).__name__}: {e})")
        return None, None


def resolve_flop_basis(measured, n_f, nx, nt, widths):
    """``(flops, basis)`` for MFU: each row keeps its OWN compiled count
    when physically plausible (a fused Taylor engine legitimately executes
    fewer logical flops than generic autodiff — its MFU is quoted on its
    own program, and ``flops_basis`` in the payload discloses that); only
    a count below the analytic floor (= a cost model blinded by a pallas
    custom call) falls back to the generic-engine basis.  A known-truncated
    count is never quoted: no basis -> no MFU.  The floor/substitution
    rules are :func:`tensordiffeq_tpu.telemetry.costmodel.resolve_flop_basis`
    (single-sourced since PR 7); this wrapper only supplies the
    bench-built generic-engine fallback."""
    from tensordiffeq_tpu.telemetry import costmodel
    return costmodel.resolve_flop_basis(
        measured, _analytic_step_floor(n_f, widths),
        fallback=lambda: generic_step_flops(n_f, nx, nt, widths))


def mfu_for(measured_flops, steps_per_sec, n_chips, n_f, nx, nt, widths):
    """``(flops, basis, mfu)`` — shared by every bench path (throughput,
    precision) so the basis/peak handling cannot drift between artifacts.
    MFU only on TPU: CPU has no meaningful peak to quote against."""
    import jax

    from tensordiffeq_tpu.telemetry import costmodel
    if jax.default_backend() != "tpu":
        return measured_flops, None, None
    flops, basis = resolve_flop_basis(measured_flops, n_f, nx, nt, widths)
    peak = costmodel.peak_flops_for(jax.devices()[0].device_kind)
    return flops, basis, costmodel.mfu(flops, steps_per_sec, n_chips, peak)


def build_solver_fallback(n_f, nx, nt, widths, fused, tag, grad_probe=False):
    """``(solver, engine_used)`` — build with the hinted engine, falling
    back to autotune when the hint cannot build (cross-check or lowering
    failure inside ``compile`` is excluded, not fatal).  ``engine_used``
    goes into the payload: measurements under different engines must be
    distinguishable.

    ``grad_probe=True`` additionally AOT-compiles ``value_and_grad``
    through the hinted engine at the real shapes before returning, so a
    hint that builds but fails when jit later differentiates through it
    (stale BENCH_ENGINE override, cross-round toolchain drift) falls back
    to autotune *here* instead of killing a long ``--full`` run 0 s in.
    One extra compile when hinted — and the persistent compile cache
    (``tensordiffeq_tpu.utils.enable_compilation_cache``) keeps it warm
    for later passes.  Modes whose own prep already AOT-compiles the step
    (``bench_jax_throughput``) skip the probe."""
    def build(f):
        solver = build_solver(n_f, nx, nt, widths, fused=f)
        if grad_probe and f != "autotune":
            import jax
            tr = {"params": solver.params, "lambdas": solver.lambdas}

            def loss_over(t):
                return solver.loss_fn(t["params"], t["lambdas"]["BCs"],
                                      t["lambdas"]["residual"], solver.X_f)

            t0 = time.time()
            jax.jit(jax.value_and_grad(loss_over, has_aux=True)) \
                .lower(tr).compile()
            log(f"[{tag}] grad-probe through fused={f!r} ok "
                f"({time.time() - t0:.1f}s)")
        return solver

    try:
        return build(fused), repr(fused)
    except Exception as e:
        if fused == "autotune":
            raise
        log(f"[{tag}] hinted engine fused={fused!r} failed "
            f"({type(e).__name__}: {e}); falling back to autotune")
        return build("autotune"), "'autotune' (hint failed)"


def bench_jax_throughput(n_f, nx, nt, widths, n_steps, fused="autotune",
                         remat=False, fused_dtype=None, minimax=None):
    import jax

    def prep(fused_arg, fd=fused_dtype, mm=minimax):
        solver = build_solver(n_f, nx, nt, widths, fused=fused_arg,
                              remat=remat, fused_dtype=fd, minimax=mm)
        t0 = time.time()
        step, trainables, opt_state = aot_compile_sa_step(solver)
        flops_per_step = compiled_flops(step)
        trainables, opt_state, loss = step(trainables, opt_state, solver.X_f)
        jax.block_until_ready(loss)
        log(f"[jax] compile+first step: {time.time() - t0:.1f}s "
            f"(backend={jax.default_backend()}, {len(jax.devices())} "
            f"device(s))")
        return solver, step, trainables, opt_state, loss, flops_per_step

    # the fallback covers the WHOLE prep — solver build, the AOT step
    # compile (which differentiates through the engine; the compile-time
    # cross-check is forward-only), and the first execution
    try:
        solver, step, trainables, opt_state, loss, flops_per_step = prep(fused)
        engine_used = repr(fused)
    except Exception as e:
        if fused == "autotune" and fused_dtype is None:
            raise
        log(f"[jax] hinted engine fused={fused!r} fused_dtype="
            f"{fused_dtype!r} failed ({type(e).__name__}: {e}); "
            f"falling back to full-precision autotune")
        # clear the dtype (and the minimax pin) too: either may itself be
        # what failed to lower
        solver, step, trainables, opt_state, loss, flops_per_step = \
            prep("autotune", None, None)
        engine_used = "'autotune' (hint failed)"
        fused_dtype = None

    t0 = time.time()
    for _ in range(n_steps):
        trainables, opt_state, loss = step(trainables, opt_state, solver.X_f)
    t_dispatched = time.time()
    jax.block_until_ready(loss)
    dt = time.time() - t0
    _record_step_split(n_steps, t_dispatched - t0, dt - (t_dispatched - t0))
    # build_solver never passes dist=True: the jitted step runs on the one
    # default device however many the host exposes, so per-chip == measured
    n_chips = 1
    pts = n_f * n_steps / dt / n_chips
    steps_per_sec = n_steps / dt

    dev_kind = jax.devices()[0].device_kind
    flops_per_step, flops_basis, mfu = mfu_for(
        flops_per_step, steps_per_sec, n_chips, n_f, nx, nt, widths)
    log(f"[jax] {n_steps} SA steps in {dt:.2f}s -> {pts:,.0f} pts/sec/chip "
        f"(loss={float(loss):.4f}, flops/step={flops_per_step} "
        f"[{flops_basis}], mfu={mfu})")
    return {"pts_per_sec_per_chip": pts, "steps_per_sec": steps_per_sec,
            "flops_per_step": flops_per_step, "flops_basis": flops_basis,
            "mfu": mfu,
            "device_kind": dev_kind, "backend": jax.default_backend(),
            "engine": engine_used + ("+remat" if remat else "")
            + (f"+{fused_dtype}" if fused_dtype else "")
            # disclose the ACTUAL loss engine (auto-adoption included)
            + (f"+minimax-{solver._minimax_kind}"
               if getattr(solver, "_minimax_kind", None) else ""),
            "loss": float(loss)}


# --------------------------------------------------------------------------- #
# TF2 reference-style baseline
# --------------------------------------------------------------------------- #
def bench_tf_baseline(n_f, nx, widths, n_steps):
    """Reference-style SA train step (networks.py MLP + nested-tape residual +
    dual-Adam minimax of fit.py:125-145), tf.function-compiled, same host."""
    import tensorflow as tf

    tf.random.set_seed(0)
    rng = np.random.RandomState(0)
    X = tf.constant(
        (rng.rand(n_f, 2) * [2.0, 1.0] - [1.0, 0.0]).astype(np.float32))
    x_f, t_f = X[:, 0:1], X[:, 1:2]
    x0 = np.linspace(-1, 1, nx).astype(np.float32).reshape(-1, 1)
    X0 = tf.constant(np.hstack([x0, np.zeros_like(x0)]))
    u0 = tf.constant((x0 ** 2 * np.cos(np.pi * x0)).astype(np.float32))

    layers = [tf.keras.layers.Input((2,))]
    for w in widths:
        layers.append(tf.keras.layers.Dense(
            w, activation="tanh", kernel_initializer="glorot_normal"))
    layers.append(tf.keras.layers.Dense(1, activation=None))
    model = tf.keras.Sequential(layers)

    lam_res = tf.Variable(rng.rand(n_f, 1).astype(np.float32))
    lam_ic = tf.Variable(100.0 * rng.rand(nx, 1).astype(np.float32))
    opt_net = tf.keras.optimizers.Adam(0.005, beta_1=0.99)
    opt_lam = tf.keras.optimizers.Adam(0.005, beta_1=0.99)

    @tf.function
    def train_step():
        with tf.GradientTape() as outer:
            with tf.GradientTape(persistent=True) as t2:
                t2.watch([x_f, t_f])
                with tf.GradientTape(persistent=True) as t1:
                    t1.watch([x_f, t_f])
                    u = model(tf.concat([x_f, t_f], 1))
                u_x = t1.gradient(u, x_f)
                u_t = t1.gradient(u, t_f)
            u_xx = t2.gradient(u_x, x_f)
            f_u = u_t - EPS * u_xx + 5.0 * u ** 3 - 5.0 * u
            loss_res = tf.reduce_mean((lam_res * f_u) ** 2)
            u0_pred = model(X0)
            loss_ic = tf.reduce_mean((lam_ic * (u0_pred - u0)) ** 2)
            loss = loss_res + loss_ic
        grads = outer.gradient(loss, model.trainable_variables + [lam_res, lam_ic])
        opt_net.apply_gradients(zip(grads[:-2], model.trainable_variables))
        opt_lam.apply_gradients([(-grads[-2], lam_res), (-grads[-1], lam_ic)])
        return loss

    t0 = time.time()
    train_step()
    log(f"[tf] trace+first step: {time.time() - t0:.1f}s")
    t0 = time.time()
    for _ in range(n_steps):
        loss = train_step()
    _ = float(loss)
    dt = time.time() - t0
    pts = n_f * n_steps / dt
    log(f"[tf] {n_steps} SA steps in {dt:.2f}s -> {pts:,.0f} pts/sec "
        f"(loss={float(loss):.4f})")
    return pts


def get_baseline(n_f, nx, widths, n_steps):
    key = f"tf_sa_pts_per_sec_nf{n_f}"
    # Cache-first: the TF step costs ~5 min of the worker's budget on this
    # 1-core host, for a number the cache already holds as best-ever (max).
    # Under a tunnel that stays healthy ~15 min at a stretch, that's the
    # difference between a promoted TPU capture and a timeout.  Set
    # BENCH_TF_FRESH=1 to force a re-measurement.
    if os.environ.get("BENCH_TF_FRESH") != "1" and os.path.exists(CACHE):
        try:
            cached = json.load(open(CACHE)).get(key)
        except (OSError, json.JSONDecodeError):
            cached = None
        if cached:
            log(f"[tf] using cached baseline {cached:,.0f} pts/s ({key})")
            return cached
    try:
        pts = bench_tf_baseline(n_f, nx, widths, n_steps)
        try:
            cache = json.load(open(CACHE)) if os.path.exists(CACHE) else {}
            # Keep the best baseline seen: a loaded host under-measures TF,
            # which would inflate vs_baseline for later TF-less runs.
            cache[key] = max(pts, cache.get(key, 0.0))
            json.dump(cache, open(CACHE, "w"), indent=1)
        except OSError:
            pass
        return pts
    except Exception as e:  # TF missing or broken: use cached measurement
        log(f"[tf] baseline unavailable ({type(e).__name__}: {e}); "
            "falling back to cached measurement")
        if os.path.exists(CACHE):
            cache = json.load(open(CACHE))
            if key in cache:
                return cache[key]
        return None


# --------------------------------------------------------------------------- #
# --engines: residual-engine comparison (generic autodiff vs fused Taylor vs
# pallas VMEM kernel) on the same SA train step
# --------------------------------------------------------------------------- #
def bench_engines(n_f, nx, nt, widths, n_steps):
    import jax

    results, errors = {}, {}
    # the engine solvers are built WITHOUT dist=True — the step runs on one
    # device regardless of how many the host has, so per-chip == measured
    n_chips = 1
    # legacy rows pin minimax=False so they keep measuring the residual
    # ENGINE alone (comparable with promoted artifacts); the fused-minimax
    # row is the whole-loss fusion on top of the best available engine
    candidates = [("generic", False, False), ("fused-xla", True, False)]
    from tensordiffeq_tpu.ops import pallas_taylor
    if pallas_taylor.available():
        candidates.append(("fused-pallas", "pallas", False))
    else:
        log("[engines] pallas excluded (no real TPU backend); it runs only "
            "in interpret mode here")
    candidates.append(("fused-minimax", True, True))
    for engine, fused, minimax in candidates:
        try:
            solver = build_solver(n_f, nx, nt, widths, fused=fused,
                                  minimax=minimax)
            t0 = time.time()
            step, trainables, opt_state = aot_compile_sa_step(solver)
            trainables, opt_state, loss = step(trainables, opt_state, solver.X_f)
            jax.block_until_ready(loss)
            compile_t = time.time() - t0
            t0 = time.time()
            for _ in range(n_steps):
                trainables, opt_state, loss = step(trainables, opt_state,
                                                   solver.X_f)
            t_disp = time.time()
            jax.block_until_ready(loss)
            dt = time.time() - t0
            _record_step_split(n_steps, t_disp - t0, dt - (t_disp - t0))
            pts = n_f * n_steps / dt / n_chips
            results[engine] = pts
            log(f"[engines] {engine}: compile {compile_t:.1f}s, "
                f"{pts:,.0f} pts/sec/chip (loss={float(loss):.4f})")
        except Exception as e:
            errors[engine] = f"{type(e).__name__}: {e}"
            log(f"[engines] {engine} FAILED: {errors[engine]}")
    return results, errors


# --------------------------------------------------------------------------- #
# --precision: float32(HIGHEST) vs bf16 matmul path on the MXU
# --------------------------------------------------------------------------- #
def bench_precision(n_f, nx, nt, widths, n_steps):
    """Measure the network's dtype/precision knobs (networks.py) as an
    actual trade-off: throughput + loss drift of each config vs the float32
    HIGHEST reference."""
    import jax

    configs = {
        "f32-highest": {"precision": jax.lax.Precision.HIGHEST},
        "f32-default": {"precision": None},
        "bf16-matmul": {"dtype": "bfloat16"},
        # mixed-precision fused Taylor engine: bf16 matmul operands with
        # f32 accumulation inside the derivative propagation (the network
        # itself stays f32) — the MXU-native path for the PINN hot loop
        # (minimax pinned OFF so the row keeps measuring the residual
        # engine alone, comparable with promoted artifacts)
        "bf16-taylor": {"fused": True, "fused_dtype": "bfloat16",
                        "minimax": False},
        # fused-minimax rows: the whole loss term — residual + SA-λ
        # weighting + reduction + every cotangent — as ONE fusion
        # (ops/pallas_minimax; the VMEM-resident kernel on real TPU, the
        # fused-XLA jaxpr elsewhere), at f32 and at bf16-matmul/f32-accum
        "f32-minimax": {"fused": True, "minimax": True},
        "bf16-minimax": {"fused": True, "fused_dtype": "bfloat16",
                         "minimax": True},
    }
    from tensordiffeq_tpu.ops import pallas_taylor
    if pallas_taylor.available():
        # the VMEM-resident kernel with bf16 matmul operands — candidate
        # fastest config on real TPU (pallas won the f32 engine race)
        configs["bf16-pallas"] = {"fused": "pallas",
                                  "fused_dtype": "bfloat16",
                                  "minimax": False}
    else:
        log("[precision] bf16-pallas excluded (no real TPU backend)")
    # single-device solvers (no dist=True): per-chip == measured
    n_chips = 1
    out = {}
    ref_loss = None
    for name, kw in configs.items():
        try:
            # bf16/precision nets bypass the fused engine (float32-only);
            # the bf16-taylor config instead keeps the f32 net and lowers
            # the fused engine's matmuls
            kw = dict(kw)
            kw.setdefault("fused", False)
            solver = build_solver(n_f, nx, nt, widths, **kw)
            step, trainables, opt_state = aot_compile_sa_step(solver)
            flops_per_step = compiled_flops(step)
            trainables, opt_state, loss = step(trainables, opt_state, solver.X_f)
            jax.block_until_ready(loss)
            t0 = time.time()
            for _ in range(n_steps):
                trainables, opt_state, loss = step(trainables, opt_state,
                                                   solver.X_f)
            t_disp = time.time()
            jax.block_until_ready(loss)
            dt = time.time() - t0
            _record_step_split(n_steps, t_disp - t0, dt - (t_disp - t0))
            loss = float(loss)
            if name == "f32-highest":
                ref_loss = loss
            # MFU per row on its own compiled count (flops_basis discloses
            # the basis; pallas rows, whose custom-call flops the cost
            # model scores at zero, fall back to the generic-engine basis
            # — see resolve_flop_basis)
            _, flops_basis, mfu = mfu_for(
                flops_per_step, n_steps / dt, n_chips, n_f, nx, nt, widths)
            out[name] = {"pts_per_sec": n_f * n_steps / dt / n_chips,
                         "loss": loss,
                         "mfu": (round(mfu, 4) if mfu is not None else None),
                         "flops_basis": flops_basis,
                         "loss_drift": (None if ref_loss is None
                                        else abs(loss - ref_loss))}
            log(f"[precision] {name}: {out[name]['pts_per_sec']:,.0f} "
                f"pts/s/chip, loss={loss:.6f}, mfu={mfu}")
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
            log(f"[precision] {name} FAILED: {out[name]['error']}")
    return out


# --------------------------------------------------------------------------- #
# --minimax: the fused minimax step vs the unfused fused-XLA path
# --------------------------------------------------------------------------- #
def bench_minimax(n_f, nx, nt, widths, n_steps):
    """Price the fused minimax STEP — residual + SA-λ-weighted loss +
    parameter cotangents + the per-point λ-ascent direction as ONE fusion
    (:mod:`tensordiffeq_tpu.ops.pallas_minimax`) — against the unfused
    path: the same fused-XLA residual engine with the loss assembled
    outside and reverse-mode AD transposing the whole chain
    (``compile(minimax=False)``).  Meaningful on CPU too (the acceptance
    bar is a measured step-time reduction there: the fusion owns its data
    layout, so the batched channel matmul's pathological AD transpose is
    replaced by the flat-GEMM custom VJP); on real TPU the engine lowers
    to the VMEM-resident pallas kernel and each arm quotes its own MFU.

    A second pair of arms (``system``/``system-unfused``) races the SAME
    comparison on a coupled 2-equation Schrödinger-type system with
    per-point λ on both channels — the widened ``[N, E]`` fused unit vs
    two generic per-equation residual terms (the multi-component
    acceptance read: fused step-time reduction ≥1.1× at drift ~0)."""
    import jax

    n_chips = 1  # single-device solvers: per-chip == measured
    arms = {}
    for name, minimax in (("unfused", False), ("minimax", True),
                          ("system-unfused", False), ("system", True)):
        system = name.startswith("system")
        try:
            if system:
                solver = build_system_solver(n_f, nx, nt, widths,
                                             minimax=minimax)
            else:
                solver = build_solver(n_f, nx, nt, widths, fused=True,
                                      minimax=minimax)
            t0 = time.time()
            step, trainables, opt_state = aot_compile_sa_step(solver)
            flops_per_step = compiled_flops(step)
            trainables, opt_state, loss = step(trainables, opt_state,
                                               solver.X_f)
            jax.block_until_ready(loss)
            compile_t = time.time() - t0
            t0 = time.time()
            for _ in range(n_steps):
                trainables, opt_state, loss = step(trainables, opt_state,
                                                   solver.X_f)
            t_disp = time.time()
            jax.block_until_ready(loss)
            dt = time.time() - t0
            _record_step_split(n_steps, t_disp - t0, dt - (t_disp - t0))
            _, flops_basis, mfu = mfu_for(
                flops_per_step, n_steps / dt, n_chips, n_f, nx, nt, widths)
            arms[name] = {
                "engine": (f"fused-minimax-{solver._minimax_kind}"
                           if minimax else "fused-xla"),
                "step_time_s": dt / n_steps,
                "pts_per_sec": n_f * n_steps / dt / n_chips,
                "loss": float(loss),
                "mfu": (round(mfu, 4) if mfu is not None else None),
                "flops_basis": flops_basis,
            }
            log(f"[minimax] {name} ({arms[name]['engine']}): compile "
                f"{compile_t:.1f}s, {arms[name]['step_time_s'] * 1e3:.2f} "
                f"ms/step, {arms[name]['pts_per_sec']:,.0f} pts/s/chip "
                f"(loss={float(loss):.6f})")
        except Exception as e:
            arms[name] = {"error": f"{type(e).__name__}: {e}"}
            log(f"[minimax] {name} FAILED: {arms[name]['error']}")

    mm, un = arms.get("minimax", {}), arms.get("unfused", {})
    if "pts_per_sec" not in mm:
        raise RuntimeError(f"minimax arm failed: {arms}")
    speedup = (round(un["step_time_s"] / mm["step_time_s"], 3)
               if "step_time_s" in un else None)

    def _rounded(arm):
        return {k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in arm.items()}

    payload = {
        "metric": ("AC-SA step time: fused-minimax vs unfused fused-XLA "
                   f"(engine: {mm['engine']})"),
        "value": round(mm["pts_per_sec"]),
        "unit": "collocation-pts/sec/chip",
        # the acceptance read: unfused step time / minimax step time
        "vs_baseline": speedup,
        "step_time_reduction": speedup,
        "minimax": _rounded(mm),
        "unfused": _rounded(un),
        "loss_drift": (abs(mm["loss"] - un["loss"])
                       if "loss" in mm and "loss" in un else None),
    }
    smm, sun = arms.get("system", {}), arms.get("system-unfused", {})
    if "pts_per_sec" in smm:
        # the coupled 2-equation arm: same read on the widened [N, E] unit
        payload["system"] = {
            "n_equations": 2,
            "step_time_reduction": (
                round(sun["step_time_s"] / smm["step_time_s"], 3)
                if "step_time_s" in sun else None),
            "loss_drift": (abs(smm["loss"] - sun["loss"])
                           if "loss" in smm and "loss" in sun else None),
            "fused": _rounded(smm),
            "unfused": _rounded(sun),
        }
    elif smm or sun:
        payload["system"] = {"error": smm.get("error") or sun.get("error")}
    return payload


# --------------------------------------------------------------------------- #
# --scale: single-chip throughput vs collocation-point count
# --------------------------------------------------------------------------- #
def _looks_oom(e: Exception) -> bool:
    """True for XLA/TPU out-of-memory failures in their usual disguises."""
    import re
    s = f"{type(e).__name__}: {e}".lower()
    return bool("resource_exhausted" in s or "resource exhausted" in s
                or "out of memory" in s or re.search(r"\boom\b", s)
                or ("allocation" in s and "exceed" in s))


def bench_scale(nx, nt, widths, n_steps, n_f_list=None, on_point=None,
                fused="autotune"):
    """Sweep N_f up to the reference's *distributed* config (AC-dist-new.py:
    N_f=500k, which the reference needs a multi-GPU MirroredStrategy for)
    and measure single-chip SA-step throughput + MFU at each size.

    ``on_point(out)`` fires after every completed point so the worker can
    stream partial payloads — a timeout on a later (larger) point must not
    discard measurements already taken."""
    fast = os.environ.get("BENCH_FAST") == "1"
    if n_f_list is None:
        if fast:
            n_f_list = [2048, 4096]
        else:
            import jax
            # the full sweep is a TPU measurement; the CPU fallback keeps
            # only sizes it can finish inside the worker budget
            n_f_list = ([10_000, 50_000] if jax.default_backend() == "cpu"
                        else [50_000, 125_000, 250_000, 500_000])
    out = {}
    for n_f in n_f_list:
        steps = max(10, n_steps * n_f_list[0] // n_f)
        try:
            try:
                r = bench_jax_throughput(n_f, nx, nt, widths, steps,
                                         fused=fused)
            except Exception as e:
                if not _looks_oom(e):
                    raise
                # HBM exhausted at this size: retry with the remat lever
                # (compile(remat=True) — backward recomputes the residual
                # chain instead of storing it) before giving up the point
                log(f"[scale] N_f={n_f} OOM ({e}); retrying with remat")
                r = bench_jax_throughput(n_f, nx, nt, widths, steps,
                                         fused=fused, remat=True)
            if "(hint failed)" in r["engine"]:  # also matches "...+remat"
                # don't re-fail a known-bad hinted engine on every
                # remaining (larger, slower-compiling) sweep point
                fused = "autotune"
            out[str(n_f)] = {"pts_per_sec": round(r["pts_per_sec_per_chip"]),
                             "engine": r["engine"],
                             "mfu": (round(r["mfu"], 4)
                                     if r["mfu"] is not None else None),
                             "flops_basis": r.get("flops_basis")}
        except Exception as e:
            out[str(n_f)] = {"error": f"{type(e).__name__}: {e}"}
            log(f"[scale] N_f={n_f} FAILED: {out[str(n_f)]['error']}")
            if fused != "autotune":
                # whatever failed, don't let a possibly-bad hint compound
                # across the remaining (larger) points
                log("[scale] dropping engine hint; autotune from here on")
                fused = "autotune"
        if on_point is not None:
            on_point(dict(out))
    return out


def scale_payload(out):
    """Payload for a (possibly partial) --scale sweep.  The multi-GPU
    comparison claim is only made when the 500k point — the size the
    reference's AC-dist-new.py needs MirroredStrategy for — actually ran."""
    ok = {k: v for k, v in out.items() if "pts_per_sec" in v}
    if not ok:
        return None
    import jax
    top = max(ok, key=lambda k: int(k))
    note = (" (the size the reference needs multi-GPU for)"
            if int(top) >= 500_000 else "")
    return {
        "metric": f"AC-SA single-chip throughput at N_f={top}{note}",
        "value": ok[top]["pts_per_sec"],
        "unit": "collocation-pts/sec/chip",
        "vs_baseline": None,
        "mfu": ok[top]["mfu"],
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "scale": out,
    }


def remat_payload(out):
    """Payload for a (possibly partial) --remat sweep.  The headline value
    is the remat-ON throughput at the LARGEST N_f that completed (remat is
    the big-N_f lever, so the mode prices it where it would be used); if no
    remat-on point succeeded the remat-OFF rate is published with the
    fallback reflected in the metric string itself instead of silently
    impersonating the remat-on number."""
    ok = {k: v for k, v in out.items() if "pts_per_sec" in v}
    if not ok:
        return None
    import jax
    on = {k: v for k, v in ok.items() if k.endswith("+remat")}
    off = {k: v for k, v in ok.items() if not k.endswith("+remat")}
    note = None
    if on:
        big = max(on, key=lambda k: int(k.split("+")[0]))
        nf_lbl = big.split("+")[0]
        src = on[big]
        base = off.get(nf_lbl)
        ratio = (round(src["pts_per_sec"] / base["pts_per_sec"], 3)
                 if base else None)
        metric = f"AC-SA step throughput with remat=True (N_f={nf_lbl})"
    else:
        big = max(off, key=int)
        nf_lbl, src, ratio = big, off[big], None
        note = "no remat-on point succeeded; value is the remat-OFF rate"
        # the metric string must carry the fallback too: consumers that
        # only keep metric/value must not read a remat-OFF rate as the
        # remat-on price
        metric = (f"AC-SA step throughput with remat=False (N_f={nf_lbl}; "
                  "remat-on failed)")
    p = {"metric": metric,
         "value": src["pts_per_sec"],
         "unit": "collocation-pts/sec/chip",
         "vs_baseline": ratio,
         "backend": jax.default_backend(),
         "device_kind": jax.devices()[0].device_kind,
         "remat": out}
    if note:
        p["note"] = note
    return p


# --------------------------------------------------------------------------- #
# --serving: batched surrogate inference through the serving subsystem
# --------------------------------------------------------------------------- #
def serving_partial(payload):
    """The salvageable grid-phase line for --serving.  It must carry a
    REAL headline (same rule as remat_payload's fallback): if the batcher
    phase dies, this line is what run_worker salvages and save_tpu_cache
    keeps as the last-good artifact, and a null value dressed in the QPS
    metric would be republished on every tunnel-down run until a full
    success overwrites it."""
    return dict(
        payload,
        metric="AC surrogate serving grid-u throughput "
               "(batcher phase incomplete)",
        value=payload["grid_u_pts_per_sec_per_chip"],
        unit="collocation-pts/sec/chip",
        note="coalesced-query phase did not complete; grid rates only")


def bench_serving(n_f, nx, nt, widths, on_phase=None):
    """Measure the serving path end-to-end: export the AC solver as a
    :class:`~tensordiffeq_tpu.serving.Surrogate`, then price

    * **dense-grid evaluation** — ``u`` and residual sweeps over a random
      grid through the :class:`InferenceEngine` (pad-to-bucket, sharded
      over all local devices off-CPU): the PACMANN-style adaptive-sampling
      workload;
    * **coalesced small queries** — many 1..32-point requests merged by
      the :class:`RequestBatcher` under its max-batch/max-latency policy:
      the heavy-traffic front-end workload.  This QPS is the headline.

    Untrained params: serving cost is shape-dependent, not value-dependent,
    so the mode never burns its budget on training.  ``on_phase(payload)``
    streams a salvageable line after each phase — a timeout in the batcher
    phase must not discard the grid rates already measured."""
    import jax

    from tensordiffeq_tpu.serving import RequestBatcher

    fast = os.environ.get("BENCH_FAST") == "1"
    solver = build_solver(n_f, nx, nt, widths)
    sur = solver.export_surrogate()
    shard = (jax.local_device_count() > 1
             and jax.default_backend() != "cpu")
    n_chips = jax.local_device_count() if shard else 1
    min_bucket, max_bucket = (64, 4096) if fast else (256, 1 << 17)
    engine = sur.engine(min_bucket=min_bucket, max_bucket=max_bucket,
                        shard=shard)

    rng = np.random.RandomState(0)

    def draw(n):
        return np.stack([rng.uniform(-1.0, 1.0, n),
                         rng.uniform(0.0, 1.0, n)], -1).astype(np.float32)

    payload = {
        "metric": "AC surrogate serving QPS (coalesced small u queries)",
        "value": None, "unit": "queries/sec/chip", "vs_baseline": None,
        "sharded_over_chips": n_chips,
        "buckets": list(engine.bucket_sizes),
    }

    # -- dense-grid phase: u then residual, compile excluded from the rate
    grid_n, reps = (8192, 3) if fast else (1 << 19, 10)
    Xg = draw(grid_n)
    for kind, fn in (("u", engine.u), ("residual", engine.residual)):
        fn(Xg)  # warm-up: the one bucket compile for this kind (the
        # engine returns host arrays, so no block_until_ready needed)
        t0 = time.time()
        for _ in range(reps):
            fn(Xg)
        dt = time.time() - t0
        payload[f"grid_{kind}_pts_per_sec_per_chip"] = round(
            grid_n * reps / dt / n_chips)
        log(f"[serving] grid {kind}: {grid_n * reps / dt:,.0f} pts/sec "
            f"({n_chips} chip(s))")
    if on_phase is not None:
        on_phase(serving_partial(payload))

    # -- coalesced-query phase: the headline.  Deterministic mixed sizes so
    # the bucket ladder (not the exact arrival shapes) bounds the compiles.
    n_req = 300 if fast else 3000
    max_batch = min(1024, max_bucket)
    # warm the u-kind ladder the coalesced batches will land on: the QPS
    # headline prices steady-state serving, and the grid phase already
    # excludes first-touch compiles from its rate the same way
    for b in engine.bucket_sizes:
        if b <= max_batch:
            engine.u(draw(b))
    from tensordiffeq_tpu.resilience import active_chaos
    chaos = active_chaos()
    resilience_kw = {}
    if chaos is not None:
        # under --chaos the batcher runs the full self-healing stack, so
        # the QPS delta vs the clean capture PRICES the recovery overhead
        from tensordiffeq_tpu.resilience import CircuitBreaker, RetryPolicy
        resilience_kw = dict(
            retry=RetryPolicy(max_attempts=4, base_delay_s=1e-3,
                              max_delay_s=1e-2),
            breaker=CircuitBreaker(failure_threshold=8,
                                   reset_timeout_s=0.05),
            request_timeout_s=10.0)
    batcher = RequestBatcher(engine, max_batch=max_batch,
                             max_latency_s=0.005, **resilience_kw)
    # under chaos, only the resilience machinery's own outcomes are
    # tolerable (an injected fault that out-lived its retries, a breaker
    # fast-fail) — they are counted in stats; an ORGANIC failure still
    # aborts the measurement either way
    from tensordiffeq_tpu.resilience import ChaosFault, CircuitOpenError
    tolerated = (ChaosFault, CircuitOpenError) if chaos is not None else ()
    sizes = rng.randint(1, 33, size=n_req)
    for s in sizes:
        try:
            batcher.submit(draw(int(s)))
            batcher.poll()
        except tolerated:
            pass
    try:
        batcher.flush()
    except tolerated:
        pass
    stats = batcher.stats()
    payload.update(
        value=(None if stats["qps"] is None
               else round(stats["qps"] / n_chips)),
        requests=stats["requests"], batches=stats["batches"],
        coalesced_points=stats["points"],
        latency_s={k: (round(v, 6) if v is not None else None)
                   for k, v in stats["latency_s"].items()},
        compile_cache_programs=engine.compile_cache_size,
        # the batcher serves engine.u, so only two kinds ever compile here
        compile_cache_bound=2 * engine.n_buckets,
        # self-healing tallies (all zero on a clean run; under --chaos the
        # retried_ok count is the faults that healed invisibly)
        serving_health={k: stats[k] for k in
                        ("failed", "timed_out", "rejected", "retried_ok")})
    log(f"[serving] {stats['requests']} requests in {stats['batches']} "
        f"batches -> {stats['qps']:,.0f} QPS, "
        f"p99={stats['latency_s']['p99']:.4f}s, "
        f"{engine.compile_cache_size} compiled programs")
    return payload


# --------------------------------------------------------------------------- #
# --fleet: multi-tenant serving through the fleet router (warm start + QPS)
# --------------------------------------------------------------------------- #
def fleet_partial(payload):
    """The salvageable warm-start-phase line for --fleet (same rule as
    serving_partial): if the multi-tenant QPS phase dies, the cold-vs-warm
    first-query measurement already taken must survive as a REAL headline,
    with the fallback disclosed in the metric string."""
    return dict(
        payload,
        metric="fleet warm-start first-query speedup "
               "(multi-tenant QPS phase incomplete)",
        value=payload["warm_start"]["speedup"],
        unit="x (cold / warm first-query latency)",
        note="multi-tenant QPS phase did not complete; warm-start "
             "measurement only")


def bench_fleet(n_f, nx, nt, widths, on_phase=None):
    """Measure the fleet layer end-to-end:

    * **warm-start phase** — export two AOT fleet artifacts, then price
      the cold-start tax: first-query latency of a cold engine (jit storm
      at request time) vs a :class:`FleetRouter`-loaded tenant (AOT warm
      start at load time).  The per-bucket compile counters prove the
      warm tenant compiled ZERO programs at request time
      (``request_time_compiles``).
    * **multi-tenant QPS phase** — the headline: N tenants x mixed
      u/residual traffic coalesced through per-tenant batchers behind
      admission control.

    Untrained params (serving cost is shape-, not value-dependent).
    ``on_phase(payload)`` streams a salvageable line after the warm-start
    phase — a timeout in the QPS grid must not discard it."""
    import shutil
    import tempfile

    from tensordiffeq_tpu import fleet
    from tensordiffeq_tpu.serving import Surrogate
    from tensordiffeq_tpu.telemetry import default_registry

    fast = os.environ.get("BENCH_FAST") == "1"
    n_tenants = 2 if fast else 4
    min_bucket, max_bucket = (64, 256) if fast else (256, 4096)
    n_chips = 1  # fleet engines serve unsharded (one tenant ladder/chip)

    work = tempfile.mkdtemp(prefix="tdq_fleet_bench_")
    tenants, f_models = [], {}
    try:
        for i in range(n_tenants):
            solver = build_solver(n_f, nx, nt, widths, seed=i)
            art = os.path.join(work, f"tenant{i}")
            fleet.export_fleet_artifact(
                solver.export_surrogate(), art,
                min_bucket=min_bucket, max_bucket=max_bucket)
            tenants.append((f"t{i}", art))
            f_models[f"t{i}"] = solver.f_model
        rng = np.random.RandomState(0)

        def draw(n):
            return np.stack([rng.uniform(-1.0, 1.0, n),
                             rng.uniform(0.0, 1.0, n)],
                            -1).astype(np.float32)

        payload = {
            "metric": "multi-tenant fleet serving QPS "
                      f"({n_tenants} tenants, mixed u/residual)",
            "value": None, "unit": "queries/sec/chip", "vs_baseline": None,
            "tenants_total": n_tenants,
            "buckets": list(min_bucket << i for i in range(
                (max_bucket // min_bucket).bit_length())),
        }

        # -- warm-start phase: cold engine vs router-warm-started tenant.
        # Distinct tenants on both sides so no jit cache is shared.
        reg = default_registry()

        def compile_count():
            return sum(v for k, v in reg.as_dict()["counters"].items()
                       if k.startswith("serving.engine.compiles"))

        cold_eng = Surrogate.load(
            tenants[0][1], f_model=f_models["t0"]).engine(
                min_bucket=min_bucket, max_bucket=max_bucket)
        Xq = draw(min_bucket)
        t0 = time.time()
        cold_eng.u(Xq)
        cold_s = time.time() - t0

        policy = fleet.TenantPolicy(min_bucket=min_bucket,
                                    max_bucket=max_bucket,
                                    max_batch=min(1024, max_bucket),
                                    max_latency_s=0.005)
        # warm first-query latency is measured BEST-OF-3 (one fresh
        # router per attempt): the number is a few ms on this throttled
        # 2-core CI host, where a single-shot measurement can eat a
        # scheduler stall and flip the >=5x contract bar (the known
        # timing flake since PR 7).  Best-of-3 removes the throttle
        # noise WITHOUT weakening the regression pin: a genuinely broken
        # warm start compiles at request time in EVERY attempt — the
        # request_time_compiles counter (summed over all three) and the
        # best-of floor both still fail.
        router = warm_lt = None
        warm_runs = []
        warm_load_s = None
        request_time_compiles = 0
        for attempt in range(3):
            r_i = fleet.FleetRouter(max_loaded=n_tenants)
            for name, art in tenants:
                r_i.register(name, art, policy=policy)
            t0 = time.time()
            lt_i = r_i.load("t1")
            load_s = time.time() - t0
            pre = compile_count()
            t0 = time.time()
            r_i.query("t1", Xq)
            warm_runs.append(time.time() - t0)
            request_time_compiles += compile_count() - pre
            if router is None:
                router, warm_lt, warm_load_s = r_i, lt_i, load_s
        warm_s = min(warm_runs)
        payload["warm_start"] = {
            "cold_first_query_s": round(cold_s, 6),
            "warm_first_query_s": round(warm_s, 6),
            "warm_first_query_s_runs": [round(w, 6) for w in warm_runs],
            "warm_load_s": round(warm_load_s, 6),
            "speedup": round(cold_s / warm_s, 2) if warm_s > 0 else None,
            "request_time_compiles": request_time_compiles,
            "aot_programs": warm_lt.warm.get("aot", 0),
            "jit_prewarmed": warm_lt.warm.get("jit", 0),
        }
        log(f"[fleet] first query: cold {cold_s * 1e3:.1f}ms vs warm "
            f"{warm_s * 1e3:.1f}ms best-of-{len(warm_runs)} "
            f"({payload['warm_start']['speedup']}x), "
            f"{request_time_compiles} request-time compiles")
        if on_phase is not None:
            on_phase(fleet_partial(payload))

        # -- multi-tenant QPS phase: mixed u/residual traffic, round-robin
        # tenants, coalesced per (tenant, kind), admission-gated.  All
        # tenants are loaded (warm) before timing: this prices steady
        # state, the warm-start phase priced the transient.
        for name, _ in tenants:
            router.load(name)

        def served_requests():
            return sum(s["requests"]
                       for t in router.stats()["tenants"].values()
                       if t["loaded"] for s in t["kinds"].values())

        n_req = 200 if fast else 2000
        sizes = rng.randint(1, 33, size=n_req)
        kinds = np.where(rng.uniform(size=n_req) < 0.7, "u", "residual")
        served_before = served_requests()  # the warm-phase probe query
        t0 = time.time()
        for i in range(n_req):
            name = tenants[i % n_tenants][0]
            router.submit(name, draw(int(sizes[i])), kind=str(kinds[i]))
            router.poll()
        router.flush()
        wall = time.time() - t0
        stats = router.stats()
        served = served_requests() - served_before
        lat = [v for t in stats["tenants"].values() if t["loaded"]
               for s in t["kinds"].values()
               for v in [s["latency_s"]] if v.get("p99") is not None]
        payload.update(
            value=round(served / wall / n_chips) if wall > 0 else None,
            requests=served, wall_s=round(wall, 3),
            latency_p99_s=(round(max(p["p99"] for p in lat), 6)
                           if lat else None),
            cache={"hits": stats["hits"], "misses": stats["misses"],
                   "evictions": stats["evictions"]},
            per_tenant={
                t: {k: {"requests": s["requests"],
                        "qps": (None if s["qps"] is None
                                else round(s["qps"], 1))}
                    for k, s in d["kinds"].items()}
                for t, d in stats["tenants"].items() if d["loaded"]},
            autoscale=router.autoscale_signals())
        log(f"[fleet] {served} requests over {n_tenants} tenants in "
            f"{wall:.2f}s -> {payload['value']:,} QPS")
        return payload
    finally:
        shutil.rmtree(work, ignore_errors=True)


# --------------------------------------------------------------------------- #
# --obs: price the observability plane (tracer + flight + collector vs bare)
# --------------------------------------------------------------------------- #
def obs_partial(payload):
    """The salvageable bare-phase line for --obs (same rule as
    fleet_partial): if the observed phase dies, the bare-fleet baseline
    already measured survives as a REAL headline."""
    bare = payload.get("bare") or {}
    if bare.get("qps") is None:
        return None
    return dict(payload,
                metric="fleet serving QPS, bare baseline "
                       "(observed phase incomplete)",
                value=bare["qps"], vs_baseline=None,
                note="observability-plane phase did not complete; "
                     "bare baseline only")


def bench_obs(n_f, nx, nt, widths, on_phase=None):
    """Price the PR-19 observability plane: the same multi-tenant
    traffic loop run bare, then fully observed — span :class:`Tracer`
    into a rotating :class:`RunLogger`, :class:`FlightRecorder` ring
    tapping every record, and a :class:`Collector` tailing the run dir
    and serving ``/metrics`` + ``/healthz``, scraped DURING traffic.

    The headline is observed QPS; ``vs_baseline`` is observed/bare (the
    plane's overhead is the shortfall from 1.0).  The bare loop runs
    twice and the spread is disclosed as ``noise_band`` — on the
    throttled CI host run-to-run jitter can exceed the plane's true
    cost, and an overhead number without its noise floor would overclaim
    precision.  Scrape latency, the flight-flush wall, the fleet
    ``/healthz`` verdict, and the trace/rotation tallies ride in the
    payload.  ``on_phase(payload)`` streams a salvageable line after the
    bare phase."""
    import shutil
    import tempfile
    import urllib.request

    from tensordiffeq_tpu import fleet, telemetry
    from tensordiffeq_tpu.telemetry import default_registry

    fast = os.environ.get("BENCH_FAST") == "1"
    n_tenants = 2
    min_bucket, max_bucket = (64, 256) if fast else (256, 1024)
    n_req = 200 if fast else 1000
    scrape_every = max(1, n_req // 10)

    work = tempfile.mkdtemp(prefix="tdq_obs_bench_")
    try:
        # ONE build + export shared by every tenant: the mode prices the
        # observability plane's overhead on multi-tenant TRAFFIC, not
        # tenant diversity — and the compile-bound setup is what blows
        # the budget when several bench workers share a throttled host
        solver = build_solver(n_f, nx, nt, widths, seed=0)
        art = os.path.join(work, "tenant")
        fleet.export_fleet_artifact(
            solver.export_surrogate(), art,
            min_bucket=min_bucket, max_bucket=max_bucket)
        tenants = [(f"t{i}", art) for i in range(n_tenants)]
        rng = np.random.RandomState(0)
        sizes = rng.randint(1, 33, size=n_req)
        kinds = np.where(rng.uniform(size=n_req) < 0.7, "u", "residual")
        queries = [np.stack([rng.uniform(-1.0, 1.0, int(n)),
                             rng.uniform(0.0, 1.0, int(n))],
                            -1).astype(np.float32) for n in sizes]
        policy = fleet.TenantPolicy(min_bucket=min_bucket,
                                    max_bucket=max_bucket,
                                    max_batch=min(1024, max_bucket),
                                    max_latency_s=0.005)

        def build_router():
            r = fleet.FleetRouter(max_loaded=n_tenants)
            for name, art in tenants:
                r.register(name, art, policy=policy)
            for name, _ in tenants:
                r.load(name)
                # compile both kinds' min-bucket rung BEFORE timing:
                # every submit below pads to that rung, so the timed
                # loops price serving, not jit
                r.query(name, queries[0], kind="u")
                r.query(name, queries[0], kind="residual")
            return r

        def run_traffic(router, on_req=None):
            t0 = time.time()
            for i in range(n_req):
                router.submit(tenants[i % n_tenants][0], queries[i],
                              kind=str(kinds[i]))
                router.poll()
                if on_req is not None:
                    on_req(i)
            router.flush()
            return time.time() - t0

        # -- bare baseline, twice: the spread IS the noise band
        bare_walls = [run_traffic(build_router()) for _ in range(2)]
        bare_wall = min(bare_walls)
        bare_qps = round(n_req / bare_wall) if bare_wall > 0 else None
        noise = (abs(bare_walls[0] - bare_walls[1]) / max(bare_walls)
                 if max(bare_walls) > 0 else None)
        payload = {
            "metric": "fleet serving QPS under the full observability "
                      f"plane ({n_tenants} tenants; tracer + flight "
                      "recorder + live collector scrapes)",
            "value": None, "unit": "queries/sec/chip",
            "vs_baseline": None,
            "bare": {"qps": bare_qps,
                     "wall_s": [round(w, 3) for w in bare_walls]},
            "noise_band": round(noise, 4) if noise is not None else None,
        }
        log(f"[obs] bare: {bare_qps:,} QPS "
            f"(noise band {noise:.1%} over 2 runs)")
        if on_phase is not None:
            partial = obs_partial(payload)
            if partial is not None:
                on_phase(partial)

        # -- observed phase: the same traffic with every instrument live
        run_dir = os.path.join(work, "run")
        scrape_ms = []
        with telemetry.RunLogger(run_dir, config={"bench": "obs"},
                                 rotate_bytes=1 << 20) as run, \
                telemetry.FlightRecorder(run_dir, capacity=256), \
                telemetry.Tracer(logger=run,
                                 registry=default_registry()):
            router = build_router()
            coll = router.serve_metrics(run_dirs=[run_dir])
            try:
                url = coll.url
                scrape_failed = [0]

                def scrape(i):
                    # a stalled scrape on an oversubscribed host is DATA
                    # (disclosed below), not a reason to abort the
                    # measurement mid-traffic
                    if i % scrape_every:
                        return
                    t0 = time.time()
                    try:
                        with urllib.request.urlopen(url + "/metrics",
                                                    timeout=10) as resp:
                            resp.read()
                    except OSError:
                        scrape_failed[0] += 1
                        return
                    scrape_ms.append((time.time() - t0) * 1e3)

                obs_wall = run_traffic(router, on_req=scrape)
                if not scrape_ms:
                    # every in-traffic scrape stalled: take one outside
                    # the timed loop so latency is still measured (a
                    # server that can't answer even now IS a failure)
                    t0 = time.time()
                    with urllib.request.urlopen(url + "/metrics",
                                                timeout=60) as resp:
                        resp.read()
                    scrape_ms.append((time.time() - t0) * 1e3)
                t0 = time.time()
                telemetry.flush_flight("bench")
                flush_ms = (time.time() - t0) * 1e3
                # an unhealthy verdict is served as HTTP 503 with the
                # SAME JSON body — on a throttled host the serving SLOs
                # may genuinely breach; that's a disclosed measurement,
                # not a failed benchmark
                try:
                    resp = urllib.request.urlopen(url + "/healthz",
                                                  timeout=60)
                except urllib.error.HTTPError as e:
                    resp = e
                with resp:
                    health = json.loads(resp.read().decode("utf-8"))
            finally:
                coll.close()

        n_trace = sum(1 for e in telemetry.read_events(run_dir)
                      if e.get("kind") == "trace")
        segments = telemetry.event_segments(run_dir)
        flight_records = telemetry.read_flight(run_dir)
        obs_qps = round(n_req / obs_wall) if obs_wall > 0 else None
        ratio = (round(obs_qps / bare_qps, 3)
                 if obs_qps and bare_qps else None)
        payload.update(
            value=obs_qps, vs_baseline=ratio,
            observed={"qps": obs_qps, "wall_s": round(obs_wall, 3)},
            overhead_fraction=(round(1.0 - ratio, 4)
                               if ratio is not None else None),
            scrapes={
                "n": len(scrape_ms),
                "failed": scrape_failed[0],
                "mean_ms": (round(sum(scrape_ms) / len(scrape_ms), 2)
                            if scrape_ms else None),
                "max_ms": (round(max(scrape_ms), 2)
                           if scrape_ms else None)},
            healthz={"ok": health.get("ok"),
                     "exit_status": health.get("exit_status")},
            flight={"flush_ms": round(flush_ms, 2),
                    "records": len(flight_records)},
            trace={"events": n_trace, "segments": len(segments)})
        log(f"[obs] observed: {obs_qps:,} QPS ({ratio}x bare; "
            f"{len(scrape_ms)} scrapes, {n_trace} trace events, "
            f"{len(segments)} log segment(s))")
        return payload
    finally:
        shutil.rmtree(work, ignore_errors=True)


# --------------------------------------------------------------------------- #
# --closedloop: one drift -> retrain -> hot-swap cycle, end to end
# --------------------------------------------------------------------------- #
def closedloop_partial(payload):
    """The salvageable detection-phase line for --closedloop (same rule
    as fleet_partial): if the retrain/swap phase dies, the drift-detection
    measurement already taken survives as a REAL headline."""
    return dict(
        payload,
        metric="closed-loop drift detection latency "
               "(retrain/swap phase incomplete)",
        value=payload["detection"]["wall_s"],
        unit="s (drift injection -> SLO trip)",
        note="retrain/swap phase did not complete; detection "
             "measurement only")


def bench_closedloop(n_f, nx, nt, widths, on_phase=None):
    """One autonomous closed-loop cycle (ROADMAP item 4), measured end to
    end: a small Allen-Cahn coefficient family is trained, exported and
    served through a :class:`~tensordiffeq_tpu.fleet.FleetRouter`; the
    served params are then perturbed in place (the drift is applied
    directly — no chaos scope, so the payload stays promotable) and the
    :class:`~tensordiffeq_tpu.fleet.DriftMonitor` must detect it from
    shadow-sampled live traffic; the
    :class:`~tensordiffeq_tpu.fleet.RetrainController` retrains the
    family warm-started from the drifted served params and hot-swaps
    every tenant behind a canary gate.

    The headline is the loop's MTTR — wall seconds from drift injection
    to every tenant cut over — decomposed into the ISSUE's four
    measurements: detection latency (queries + wall from injection to
    SLO trip), retrain wall, swap cutover stall p50 (the only pause a
    waiter can observe), and post-swap residual improvement (drifted /
    post-swap probe residual, >1 means the loop healed the fleet).
    ``request_time_compiles`` proves the cutover compiled nothing at
    request time.  ``on_phase(payload)`` streams a salvageable line
    after the detection phase."""
    import shutil
    import tempfile

    import jax
    from tensordiffeq_tpu import (IC, DomainND, SurrogateFactory, fleet,
                                  grad, periodicBC)
    from tensordiffeq_tpu.telemetry import default_registry

    fast = os.environ.get("BENCH_FAST") == "1"
    n_members = 2 if fast else 4
    min_bucket, max_bucket = (64, 256) if fast else (256, 4096)
    pre_iters = 60 if fast else 600
    retrain_iters = 60 if fast else 600
    chunk = 20 if fast else 100
    drift_scale = 0.8
    thetas = [0.0009 + 0.0002 * m for m in range(n_members)]

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], nx)
    domain.add("t", [0.0, 1.0], nt)
    domain.generate_collocation_points(min(n_f, 2048 if fast else 10_000),
                                       seed=0)

    def func_ic(x):
        return x ** 2 * np.cos(np.pi * x)

    def deriv_model(u, x, t):
        return u(x, t), grad(u, "x")(x, t)

    bcs = [IC(domain, [func_ic], var=[["x"]]),
           periodicBC(domain, ["x"], [deriv_model])]

    def f_model(u, x, t, th):
        u_xx = grad(grad(u, "x"), "x")
        u_t = grad(u, "t")
        uv = u(x, t)
        return u_t(x, t) - th * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv

    def build_factory(init_params=None):
        return SurrogateFactory(widths_to_layers(widths), f_model, domain,
                                bcs, thetas, init_params=init_params,
                                verbose=False)

    def widths_to_layers(ws):
        return [2] + list(ws) + [1]

    rng = np.random.RandomState(0)

    def draw(n):
        return np.stack([rng.uniform(-1.0, 1.0, n),
                         rng.uniform(0.0, 1.0, n)],
                        -1).astype(np.float32)

    reg = default_registry()

    def compile_count():
        return sum(v for k, v in reg.as_dict()["counters"].items()
                   if k.startswith("serving.engine.compiles"))

    work = tempfile.mkdtemp(prefix="tdq_closedloop_bench_")
    try:
        # -- v1: train, export, serve, monitor --------------------------- #
        factory = build_factory()
        factory.fit(tf_iter=pre_iters, chunk=chunk)
        v1 = os.path.join(work, "v1")
        factory.export_family(v1, min_bucket=min_bucket,
                              max_bucket=max_bucket)
        router = fleet.FleetRouter(max_loaded=n_members + 1)
        policy = fleet.TenantPolicy(min_bucket=min_bucket,
                                    max_bucket=max_bucket,
                                    max_batch=min(1024, max_bucket),
                                    max_latency_s=0.005)
        members = router.register_family(
            v1, policy=policy, prefix="m",
            f_models={m: factory.member_f_model(m)
                      for m in range(n_members)})
        monitor = fleet.DriftMonitor(router, sample_fraction=0.5,
                                     window=2, seed=0)
        probe = draw(min_bucket)
        for tenant in members.values():
            router.load(tenant)
            monitor.attach(tenant, probe)

        # -- drift injection + detection --------------------------------- #
        # perturb every tenant's SERVED params in place (the engine reads
        # them at call time) and serve traffic until the monitor trips
        for tenant in members.values():
            lt = router.load(tenant)
            lt.surrogate.params = jax.tree_util.tree_map(
                lambda a: a * (1.0 + drift_scale), lt.surrogate.params)
        drifted_res = float(np.mean([
            np.mean(np.abs(np.asarray(
                router.load(t).engine.residual(probe))))
            for t in members.values()]))
        t0 = time.time()
        queries_to_trip = 0
        while not monitor.tripped() and queries_to_trip < 500:
            tenant = list(members.values())[
                queries_to_trip % len(members)]
            monitor.query(tenant, draw(int(rng.randint(1, 33))))
            queries_to_trip += 1
        detect_wall = time.time() - t0
        payload = {
            "metric": "closed-loop MTTR: drift injection -> every tenant "
                      f"hot-swapped ({len(members)} tenants)",
            "value": None, "unit": "s", "vs_baseline": None,
            "tenants": len(members),
            "detection": {
                "wall_s": round(detect_wall, 4),
                "queries_to_trip": queries_to_trip,
                "tripped": list(monitor.tripped()),
                "drift_level": max(
                    monitor.drift(t) or 0.0 for t in members.values()),
                "slo": monitor.evaluate()["objectives"]["residual_drift"],
            },
        }
        log(f"[closedloop] drift tripped after {queries_to_trip} queries "
            f"({detect_wall:.2f}s): level "
            f"{payload['detection']['drift_level']:.1f}x")
        if on_phase is not None:
            on_phase(closedloop_partial(payload))

        # -- retrain + hot-swap ------------------------------------------ #
        controller = fleet.RetrainController(
            router, monitor, build_factory, members,
            retrain_iters=retrain_iters, chunk=chunk,
            resample_every=0,  # disclosed: redraw compile excluded here
            gate_ratio=10.0,   # permissive gate; improvement is REPORTED
            export_kw=dict(min_bucket=min_bucket, max_bucket=max_bucket),
            workdir=work, verbose=False)
        cycle = controller.run_cycle()
        pre = compile_count()
        post_res = float(np.mean([
            np.mean(np.abs(np.asarray(
                router.load(t).engine.residual(probe))))
            for t in members.values()]))
        for tenant in members.values():  # post-swap serve: zero compiles
            router.query(tenant, draw(16))
        request_time_compiles = compile_count() - pre
        stalls = sorted(v["cutover_stall_s"] for v in cycle["swapped"])
        payload.update(
            value=round(detect_wall + cycle["retrain_wall_s"]
                        + sum(stalls), 3),
            retrain={"wall_s": round(cycle["retrain_wall_s"], 3),
                     "epochs": cycle["retrain_epochs"],
                     "generations": cycle["generations"]},
            swap={
                "swapped": len(cycle["swapped"]),
                "rolled_back": len(cycle["rolled_back"]),
                "cutover_stall_p50_s": (
                    round(stalls[len(stalls) // 2], 6) if stalls
                    else None),
                "request_time_compiles": request_time_compiles,
            },
            residual={"baseline": round(float(np.mean(
                          [monitor.baseline(t)
                           for t in members.values()])), 6),
                      "drifted": round(drifted_res, 6),
                      "post_swap": round(post_res, 6),
                      "improvement": (round(drifted_res / post_res, 2)
                                      if post_res > 0 else None)})
        log(f"[closedloop] retrain {cycle['retrain_wall_s']:.1f}s, "
            f"{len(cycle['swapped'])}/{len(members)} swapped, residual "
            f"{drifted_res:.3e} -> {post_res:.3e} "
            f"({payload['residual']['improvement']}x), "
            f"{request_time_compiles} request-time compiles")
        return payload
    finally:
        shutil.rmtree(work, ignore_errors=True)


# --------------------------------------------------------------------------- #
# --mode factory: family-of-M vmapped training vs the sequential baseline
# --------------------------------------------------------------------------- #
def bench_factory(n_f, nx, nt, widths, n_steps, n_members=64):
    """The surrogate-factory throughput race (ROADMAP item 3): train a
    ``n_members``-member Allen-Cahn coefficient sweep as ONE vmapped
    program (:class:`tensordiffeq_tpu.factory.SurrogateFactory`) vs the
    same members trained SEQUENTIALLY, and report aggregate
    collocation-pts/s for both arms.

    TWO sequential baselines, both disclosed:

    * ``sequential`` (the REAL arm, the acceptance denominator): one
      :class:`CollocationSolverND` per member — the repo's canonical
      way to train one coefficient, and therefore the canonical way to
      train 64 of them without the factory.  Each member pays its own
      engine adoption + program build (distinct θ ⇒ distinct program):
      the cost the factory's ONE-program property deletes.  Measured
      end-to-end (compile + fit) on a member sample and extrapolated
      linearly (identical per-member work; sample size disclosed).
    * ``sequential_shared_scan`` (the idealized steady-state arm): one
      compiled scan-chunked member step with θ as a traced operand, so
      all members share a single program — this arm already GRANTS the
      sequential side half the factory's trick and isolates the pure
      vmap win (batched ops amortize per-op overhead; on a 2-core CPU
      host this is a modest factor, on the MXU it is the chip-filling
      claim PERF.md stages for TPU capture).

    The family arm is measured THROUGH ``SurrogateFactory.fit`` — its
    per-chunk host bookkeeping (history, divergence masking) counts
    against it.  All arms run the same member math at the same sizes
    from the same per-member initializations."""
    from functools import partial

    import optax

    import jax
    import jax.numpy as jnp
    from tensordiffeq_tpu import (IC, DomainND, SurrogateFactory, grad,
                                  periodicBC)
    from tensordiffeq_tpu.training.fit import make_optimizer

    M = int(n_members)

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], nx)
    domain.add("t", [0.0, 1.0], nt)
    domain.generate_collocation_points(n_f, seed=0)

    def func_ic(x):
        return x ** 2 * np.cos(np.pi * x)

    def deriv_model(u, x, t):
        return u(x, t), grad(u, "x")(x, t)

    bcs = [IC(domain, [func_ic], var=[["x"]]),
           periodicBC(domain, ["x"], [deriv_model])]

    def f_model(u, x, t, th):
        u_xx = grad(grad(u, "x"), "x")
        u_t = grad(u, "t")
        uv = u(x, t)
        return u_t(x, t) - th * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv

    # the coefficient sweep: a neighborhood around the reference EPS —
    # exactly the "users ask for *their* coefficients" workload
    thetas = [EPS * (0.5 + m / max(M - 1, 1)) for m in range(M)]
    lam0 = np.ones((n_f, 1), np.float32)

    # -- family arm, END-TO-END: factory build (template engine
    # adoption + family cross-check) + the ONE program build + the
    # training budget — the same accounting the sequential-solver arm
    # gets, so neither side hides its compiles
    t_e2e = time.time()
    fac = SurrogateFactory(
        [2, *widths, 1], f_model, domain, bcs, thetas=thetas,
        Adaptive_type=1,
        dict_adaptive={"residual": [True], "BCs": [False, False]},
        init_weights={"residual": [lam0], "BCs": [None, None]},
        seed=0, verbose=False)
    log(f"[factory] family of {M} compiled ({fac.engine} engine)")
    fac.fit(tf_iter=n_steps, chunk=n_steps)
    fam_e2e_wall = time.time() - t_e2e
    fam_pts = M * n_f * n_steps / fam_e2e_wall
    # steady state: a second fit reuses the factory's cached compiled
    # runner — the per-chunk rate once the one-time build is paid
    t0 = time.time()
    fac.fit(tf_iter=n_steps, chunk=n_steps)
    fam_steady_wall = time.time() - t0
    fam_steady_pts = M * n_f * n_steps / fam_steady_wall

    # -- sequential arm: one scan-chunked member program, θ an operand
    opt = make_optimizer()
    member_vg = fac._member_vg

    @partial(jax.jit, static_argnames=("n",))
    def seq_run(tr, opt_state, X, theta, n):
        def step(carry, i):
            tr, opt_state = carry
            total, comps, grads, gnorm = member_vg(tr, X, theta)
            updates, opt_state = opt.update(grads, opt_state, tr)
            return (optax.apply_updates(tr, updates), opt_state), total
        (tr, opt_state), totals = jax.lax.scan(
            step, (tr, opt_state), jnp.arange(n))
        return tr, opt_state, totals

    states = []
    for m in range(M):
        # the same per-member initializations the family started from
        # (PRNGKey(seed + m); the trained fac stack must not leak in)
        p_m = fac.net.init(jax.random.PRNGKey(m),
                           jnp.zeros((1, 2), jnp.float32))
        tr = {"params": p_m,
              "lambdas": {"residual": [jnp.asarray(lam0)], "BCs": []}}
        states.append((tr, opt.init(tr),
                       jnp.asarray(thetas[m], jnp.float32)))
    X0 = fac.X_f[0]
    # warm-up: compile the one shared program
    out = seq_run(states[0][0], states[0][1], X0, states[0][2], n_steps)
    jax.block_until_ready(out)
    t0 = time.time()
    finals = []
    for tr, st, th in states:
        tr, st, totals = seq_run(tr, st, X0, th, n_steps)
        finals.append(totals)
    jax.block_until_ready(finals)
    scan_wall = time.time() - t0
    scan_pts = M * n_f * n_steps / scan_wall

    # -- the REAL sequential arm: one CollocationSolverND per member,
    # end-to-end (engine adoption + program build + fit) — distinct θ
    # means a distinct program per member, which is exactly the cost
    # the factory's one-program family step deletes.  Per-member work
    # is identical, so a member sample prices the arm; the sample size
    # is disclosed and the extrapolation is linear.
    from tensordiffeq_tpu import CollocationSolverND
    n_sample = min(4 if os.environ.get("BENCH_FAST") == "1" else 8, M)
    solver_walls = []
    for m in range(n_sample):
        th = thetas[m]

        def f_m(u, x, t, _th=th):
            return f_model(u, x, t, _th)

        t0 = time.time()
        s = CollocationSolverND(verbose=False, seed=m)
        s.compile([2, *widths, 1], f_m, domain, bcs, Adaptive_type=1,
                  dict_adaptive={"residual": [True],
                                 "BCs": [False, False]},
                  init_weights={"residual": [lam0],
                                "BCs": [None, None]})
        s.fit(tf_iter=n_steps, chunk=n_steps)
        solver_walls.append(time.time() - t0)
    seq_member_wall = float(np.mean(solver_walls))
    seq_wall = seq_member_wall * M
    seq_pts = M * n_f * n_steps / seq_wall

    payload = {
        "metric": f"surrogate-factory family-of-{M} aggregate training "
                  "throughput (vmapped one-program family vs sequential "
                  "per-member solvers)",
        "value": round(fam_pts),
        "unit": "collocation-pts/sec/chip",
        "vs_baseline": round(fam_pts / seq_pts, 3) if seq_pts > 0 else None,
        "members": M,
        "n_f_per_member": n_f,
        "steps": n_steps,
        "engine": f"family-{fac.engine}",
        "members_frozen": len(fac.frozen_at),
        "family": {"pts_per_sec": round(fam_pts),
                   "wall_s": round(fam_e2e_wall, 4),
                   "steady_state_pts_per_sec": round(fam_steady_pts),
                   "steady_state_wall_s": round(fam_steady_wall, 4)},
        "sequential": {
            "pts_per_sec": round(seq_pts),
            "wall_s": round(seq_wall, 4),
            "per_member_wall_s": round(seq_member_wall, 4),
            "sampled_members": n_sample,
            "arm": "one CollocationSolverND per member, end-to-end "
                   "(engine adoption + program build + fit; distinct "
                   "theta = distinct program) — the repo's canonical "
                   "per-member path, linearly extrapolated from the "
                   "sampled members"},
        "sequential_shared_scan": {
            "pts_per_sec": round(scan_pts),
            "wall_s": round(scan_wall, 4),
            "vs_family_steady_state": round(fam_steady_pts / scan_pts, 3)
            if scan_pts > 0 else None,
            "arm": "idealized steady-state: one shared compiled scan "
                   "(theta as operand) — grants the sequential side "
                   "the factory's one-program property and isolates "
                   "the pure vmap factor (MXU-bound on TPU; modest on "
                   "this 2-core CPU host)"},
    }
    log(f"[factory] family {fam_pts:,.0f} pts/s vs sequential-solver "
        f"{seq_pts:,.0f} pts/s -> {payload['vs_baseline']}x "
        f"(shared-scan arm {scan_pts:,.0f} pts/s; {M} members, "
        f"N_f={n_f}, {n_steps} steps)")
    return payload


# --------------------------------------------------------------------------- #
# --full: real training with periodic L2 evaluation -> time-to-target
# --------------------------------------------------------------------------- #
def bench_time_to_l2(n_f, nx, nt, widths, target=2.1e-2,
                     adam_iter=10_000, newton_iter=10_000,
                     eval_every=1_000, on_eval=None, fused="autotune"):
    """``on_eval(snapshot)`` fires at every periodic evaluation so the
    worker can stream partial payloads — a tunnel death 80 minutes into
    the full run must still leave the rel-L2 progress on record (the
    supervisor's salvage path tags the last streamed line "partial").

    Cross-window resume: the run checkpoints its full training state
    every eval (``fit(checkpoint_dir=)``, ``BENCH_FULL_CKPT`` overrides
    the location, empty disables) and picks up from the checkpoint on the
    next invocation — two 45-minute tunnel windows compose into one
    complete 90-minute north-star run instead of two lost halves.
    ``wall``/timeline times are cumulative PRODUCTIVE time across
    windows (tunnel downtime between windows excluded, ``windows``
    counts the attempts)."""
    from tensordiffeq_tpu.exact import allen_cahn_solution
    from tensordiffeq_tpu.helpers import find_L2_error

    xg, tg, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(xg, tg, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)

    solver, engine_used = build_solver_fallback(n_f, nx, nt, widths, fused,
                                                "full", grad_probe=True)
    ckpt = os.environ.get("BENCH_FULL_CKPT", "runs/full_ckpt")
    fast = os.environ.get("BENCH_FAST") == "1"
    if fast and "BENCH_FULL_CKPT" not in os.environ:
        ckpt = ""  # smoke runs must not seed a resume point for real runs
    meta_path = os.path.join(ckpt, "bench_meta.json") if ckpt else None
    timeline = []
    t_target = None
    t_prev = 0.0
    adam_done = 0
    newton_done = 0
    windows = 1
    if ckpt and os.path.exists(os.path.join(ckpt, "tdq_meta.json")):
        try:
            solver.restore_checkpoint(ckpt)
            adam_done = min(len(solver.losses), adam_iter)
            newton_done = min(getattr(solver, "newton_done", 0), newton_iter)
            try:
                with open(meta_path) as fh:
                    m = json.load(fh)
                timeline = list(m.get("timeline", []))
                t_prev = float(m.get("train_wall", 0.0))
                t_target = m.get("t_target")
                windows = int(m.get("windows", 1)) + 1
            except Exception:
                pass  # solver state alone still saves the training time
            log(f"[full] resumed from {ckpt}: {adam_done} Adam epochs, "
                f"{newton_done} L-BFGS iters, {t_prev:.0f}s productive "
                f"time, window #{windows}")
        except Exception as e:
            log(f"[full] checkpoint in {ckpt} not restorable "
                f"({type(e).__name__}: {e}); starting fresh")
    Xg_j = None  # device copy, created lazily on first eval
    t0 = time.time()

    # ONE continuous fit: the in-run eval hook fires at chunk boundaries, so
    # optimizer state, L-BFGS curvature memory, and the compiled runners stay
    # warm — the wall clock measures a single uninterrupted 10k+10k run (the
    # rel-L2 eval itself, one forward over the fixture grid per eval_every
    # epochs, is included; it is negligible next to a training chunk)
    def eval_fn(phase, step, params):
        nonlocal t_target, Xg_j
        import jax.numpy as jnp
        if Xg_j is None:
            Xg_j = jnp.asarray(Xg, jnp.float32)
        u_pred = np.asarray(solver._apply_jit(params, Xg_j))
        l2 = float(find_L2_error(u_pred, u_star))
        t = t_prev + time.time() - t0
        # offset by the prior windows' progress in EACH phase so resumed
        # timelines never repeat a label for different absolute iterations
        abs_step = step + (adam_done if phase == "adam" else newton_done)
        timeline.append({"t": round(t, 1), "phase": f"{phase}@{abs_step}",
                         "l2": l2})
        if t_target is None and l2 <= target:
            t_target = round(t, 1)
        log(f"[full] t={t:7.1f}s {phase}@{abs_step}: rel-L2={l2:.3e}")
        if meta_path is not None:
            # written AFTER fit's same-boundary checkpoint: the resume
            # meta is never newer than the state it describes
            try:
                with open(meta_path, "w") as fh:
                    json.dump({"timeline": timeline, "train_wall": t,
                               "t_target": t_target, "windows": windows},
                              fh)
            except Exception:
                pass
        if on_eval is not None:
            on_eval({"wall": round(t, 1), "l2": l2, "t_target": t_target,
                     "engine": engine_used, "windows": windows,
                     "timeline": list(timeline)})

    # metrics-only telemetry (no JSONL, no raise, and grad_norm=False so
    # the compiled step stays bit-identical to earlier captures of this
    # headline): the trainer's fenced adam/l-bfgs step-time split rides
    # into the payload's telemetry block; a NaN here must surface through
    # the artifact, not kill the capture
    from tensordiffeq_tpu.telemetry import TrainingTelemetry
    solver.fit(tf_iter=adam_iter - adam_done,
               newton_iter=newton_iter - newton_done,
               eval_fn=eval_fn, eval_every=eval_every,
               checkpoint_dir=(ckpt or None), checkpoint_every=eval_every,
               telemetry=TrainingTelemetry(logger=None, log_every=0,
                                           raise_on_divergence=False,
                                           grad_norm=False))
    wall = t_prev + time.time() - t0
    u_pred, _ = solver.predict(Xg, best_model=True)
    l2_best = float(find_L2_error(u_pred, u_star))
    if ckpt:
        # the run COMPLETED: clear the resume point so a future fresh
        # measurement can never silently resume this finished run and
        # report stale cumulative numbers
        import shutil
        for d in (ckpt, ckpt + ".old", ckpt + ".tmp"):
            shutil.rmtree(d, ignore_errors=True)
    log(f"[full] wall={wall:.1f}s best rel-L2={l2_best:.3e} "
        f"(target {target:g}, reached at t={t_target}, "
        f"{windows} window(s))")
    return {"wall": wall, "l2": l2_best, "t_target": t_target,
            "engine": engine_used, "windows": windows, "timeline": timeline}


def bench_resample(n_f, widths, adam_iter, newton_iter, resample_every,
                   eval_every, gate, on_arm=None):
    """``--resample``: the adaptive-collocation race + the redraw's cost.

    Burgers (the zoo problem where the 3-seed ablation proved the
    adaptive win — CONVERGENCE.md, ``runs/resample_ablation.json``),
    three arms at equal N_f and equal optimizer budget (``adam_iter``
    Adam epochs then ``newton_iter`` L-BFGS iterations — the refinement
    phase is IN the race because that is where point placement pays:
    L-BFGS polishes whatever the point set can express, and a fixed draw
    that undersamples the shock plateaus there while the resampled set
    keeps converging, exactly the ablation's seed-0 separation):

    * ``fixed``            — one LHS draw for the whole run (reference
      behavior),
    * ``adaptive-host``    — residual-importance redraw, original host
      path (``resample_device=False``: numpy pool, scores pulled to
      host, synchronous),
    * ``adaptive-device``  — the device-resident redraw, pipelined
      behind the training chunks (the default path),
    * ``pacmann``          — the gradient-ascent mover
      (``resample_mode="ascent"``, arXiv:2411.19632): retained points
      climb the residual-magnitude landscape instead of being redrawn
      from a pool; same pipelined one-program contract as the device
      path, scored through the fused step's own ∂/∂X cotangent when the
      minimax engine is adopted.

    Two headline reads: (1) *steps-to-rel-L2-gate* — the cumulative
    optimizer step (Adam epochs + L-BFGS iterations) of the first
    periodic evaluation at or under ``gate`` (resolution =
    ``eval_every``), the production meaning of adaptive placement being
    "faster"; (2) the *redraw wall-time split* — per-redraw host-visible
    stall (``resample.stall_s``), where the pipelined path should pay
    ~ms of dispatch+swap bookkeeping against the host path's full
    synchronous pool→score→select→device_put round trip.  The stall
    histogram's p50 is the steady-state per-redraw number (the device
    arm's FIRST redraw carries the one-time jit compile of the redraw
    program; the mean is disclosed alongside).  ``on_arm(arms)`` fires
    after each completed arm so the worker can stream salvageable
    partials."""
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC,
                                  dirichletBC, grad)
    from tensordiffeq_tpu.exact import burgers_solution
    from tensordiffeq_tpu.telemetry import MetricsRegistry, TrainingTelemetry

    x, t, usol = burgers_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"),
                  -1).reshape(-1, 2).astype(np.float32)
    u_star = usol.reshape(-1, 1)

    def build():
        domain = DomainND(["x", "t"], time_var="t")
        domain.add("x", [-1.0, 1.0], 256)
        domain.add("t", [0.0, 1.0], 100)
        domain.generate_collocation_points(n_f, seed=0)
        bcs = [IC(domain, [lambda xx: -np.sin(np.pi * xx)], var=[["x"]]),
               dirichletBC(domain, val=0.0, var="x", target="upper"),
               dirichletBC(domain, val=0.0, var="x", target="lower")]

        def f_model(u, xx, tt):
            u_x, u_t = grad(u, "x"), grad(u, "t")
            u_xx = grad(u_x, "x")
            return (u_t(xx, tt) + u(xx, tt) * u_x(xx, tt)
                    - (0.01 / np.pi) * u_xx(xx, tt))

        solver = CollocationSolverND(verbose=False)
        solver.compile([2, *widths, 1], f_model, domain, bcs)
        return solver

    arms = {}

    def run_arm(name, **fit_kw):
        solver = build()
        reg = MetricsRegistry()
        tele = TrainingTelemetry(logger=None, registry=reg, log_every=0,
                                 grad_norm=False, raise_on_divergence=False)
        hit, last_l2 = [], [None]

        def eval_fn(phase, step, params):
            u_pred = np.asarray(solver._apply_jit(params, Xg))
            l2 = float(tdq.find_L2_error(u_pred, u_star))
            last_l2[0] = l2
            total = step + (adam_iter if phase != "adam" else 0)
            if not hit and l2 <= gate:
                hit.append(total)

        t0 = time.time()
        solver.fit(tf_iter=adam_iter, newton_iter=newton_iter,
                   eval_fn=eval_fn, eval_every=eval_every, telemetry=tele,
                   **fit_kw)
        wall = time.time() - t0
        snap = reg.as_dict()
        stall = snap["histograms"].get("resample.stall_s")
        arm = {"epochs_to_gate": hit[0] if hit else None,
               "rel_l2_final": round(last_l2[0], 5), "wall_s": round(wall, 1),
               "redraws": snap["counters"].get("resample.redraws", 0)}
        if stall is not None:
            arm["stall_s"] = {k: round(float(stall[k]), 5)
                              for k in ("mean", "p50", "p99", "max")
                              if stall.get(k) is not None}
            for g in ("resample.kept_fraction", "resample.score_gain",
                      "resample.ascent_steps"):
                if g in snap["gauges"]:
                    arm[g.split(".", 1)[1]] = round(snap["gauges"][g], 4)
        arms[name] = arm
        log(f"[resample] {name}: epochs_to_gate={arm['epochs_to_gate']} "
            f"rel_l2_final={arm['rel_l2_final']} wall={arm['wall_s']}s "
            f"redraws={arm['redraws']}")
        if on_arm is not None:
            on_arm(arms)

    run_arm("fixed")
    run_arm("adaptive-host", resample_every=resample_every,
            resample_device=False, resample_seed=1)
    run_arm("adaptive-device", resample_every=resample_every,
            resample_seed=1)
    # ascent knobs measured on this config: 3 steps at the default
    # step_frac resolve the shock ridge without overshooting it, and the
    # 0.3 coverage floor keeps the moved set from collapsing onto it
    # (fresh 0.1 final-l2'd 6x worse; step_frac 0.02 never gated)
    run_arm("pacmann", resample_every=resample_every,
            resample_seed=1, resample_mode="ascent",
            resample_ascent_steps=3, resample_uniform=0.3)
    return resample_payload(arms, gate=gate, n_f=n_f,
                            budget=adam_iter + newton_iter,
                            resample_every=resample_every)


def resample_payload(arms, gate, n_f, budget, resample_every):
    """One-JSON-line payload for the resample race (also the per-arm
    streaming partial).  Headline: epochs-to-gate speedup of the
    device-resident adaptive arm over fixed LHS (>1 = adaptive reaches
    the accuracy bar in fewer epochs at equal N_f).  A fixed arm that
    never reached the gate inside the budget lower-bounds the speedup
    (disclosed in ``note``); an adaptive arm that never reached it
    reports ``value: null`` rather than impersonating a win.  The
    redraw-stall split (``redraw_stall_*``) compares the adaptive arms'
    steady-state (p50) per-redraw host-visible stall.  The ``pacmann``
    (ascent-mover) arm adds a third read: its steps-to-gate against the
    pool→top-k device arm (``pacmann_vs_pool`` ≤ 1 means the mover
    reaches the bar in no more steps than the redraw)."""
    if not arms:
        return None
    payload = {
        "metric": f"Burgers steps-to-rel-L2<={gate:g}: fixed LHS vs "
                  "adaptive vs adaptive+device-pipelined redraw vs "
                  "PACMANN ascent mover "
                  f"(N_f={n_f}, {budget} Adam+L-BFGS steps, "
                  f"resample_every={resample_every})",
        "value": None, "unit": "x fewer steps to rel-L2 gate",
        "vs_baseline": None, "gate_rel_l2": gate, "arms": arms,
    }
    fixed = arms.get("fixed")
    dev = arms.get("adaptive-device")
    host = arms.get("adaptive-host")
    pac = arms.get("pacmann")
    if len(arms) < 4:
        payload["partial"] = (f"only {sorted(arms)} completed; "
                              "arms missing from this line died or are "
                              "still running")
    if dev is not None and fixed is not None:
        e_dev, e_fix = dev["epochs_to_gate"], fixed["epochs_to_gate"]
        if e_dev is not None:
            if e_fix is not None:
                payload["value"] = round(e_fix / e_dev, 3)
            else:
                # fixed never got there: the full budget is the tightest
                # defensible denominator — a LOWER bound on the speedup
                payload["value"] = round(budget / e_dev, 3)
                payload["note"] = (
                    f"fixed-LHS arm never reached the gate in {budget} "
                    "optimizer steps; speedup quoted against the full "
                    "budget is a lower bound")
            payload["vs_baseline"] = payload["value"]
    if pac is not None:
        e_pac = pac["epochs_to_gate"]
        if e_pac is not None and fixed is not None:
            e_fix = fixed["epochs_to_gate"]
            payload["pacmann_vs_fixed"] = (
                round(e_fix / e_pac, 3) if e_fix is not None
                else round(budget / e_pac, 3))
        if (e_pac is not None and dev is not None
                and dev["epochs_to_gate"] is not None):
            # ≤ 1 means the ascent mover needs no more steps than the
            # pool→top-k redraw at the same cadence/budget
            payload["pacmann_vs_pool"] = round(
                e_pac / dev["epochs_to_gate"], 3)
    stalls = {n: a["stall_s"] for n, a in
              (("host", host), ("device", dev), ("pacmann", pac))
              if a is not None and "stall_s" in a}
    if stalls:
        payload["redraw_stall_s_p50"] = {n: s["p50"]
                                         for n, s in stalls.items()}
        payload["redraw_stall_s_mean"] = {n: s["mean"]
                                          for n, s in stalls.items()}
        if ("host" in stalls and "device" in stalls
                and stalls["device"]["p50"] > 0):
            payload["redraw_stall_reduction"] = round(
                stalls["host"]["p50"] / stalls["device"]["p50"], 2)
    return payload


# --------------------------------------------------------------------------- #
# worker / supervisor
# --------------------------------------------------------------------------- #
def worker_main(args):
    if args.force_cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    chaos = None
    if getattr(args, "chaos", None):
        from tensordiffeq_tpu.resilience import Chaos
        chaos = Chaos.from_spec(args.chaos)
        chaos.__enter__()  # worker-lifetime scope (process exits after)
        log(f"[chaos] fault injection active: {chaos.spec()}")

    fast = os.environ.get("BENCH_FAST") == "1"
    n_f = int(os.environ.get("BENCH_NF", 2048 if fast else 50_000))
    n_steps = int(os.environ.get("BENCH_STEPS", 10 if fast else 100))
    nx, nt = (64, 16) if fast else (512, 201)
    widths = [32, 32] if fast else [128, 128, 128, 128]

    if args.engines:
        results, errors = bench_engines(n_f, nx, nt, widths, n_steps)
        if not results:
            raise RuntimeError(f"all engines failed: {errors}")
        best = max(results, key=results.get)
        payload = {
            "metric": f"AC-SA step throughput by engine (best: {best})",
            "value": round(results[best]),
            "unit": "collocation-pts/sec/chip",
            "vs_baseline": round(results[best] / results["generic"], 3)
            if "generic" in results else None,
            "engines": {k: round(v) for k, v in results.items()},
        }
        if errors:
            payload["engine_errors"] = errors
    elif args.precision:
        out = bench_precision(n_f, nx, nt, widths, n_steps)
        ok = {k: v for k, v in out.items() if "pts_per_sec" in v}
        if not ok:
            raise RuntimeError(f"all precision configs failed: {out}")
        best = max(ok, key=lambda k: ok[k]["pts_per_sec"])
        ref = ok.get("f32-highest", {}).get("pts_per_sec")
        payload = {
            "metric": f"AC-SA step throughput by precision (best: {best})",
            "value": round(ok[best]["pts_per_sec"]),
            "unit": "collocation-pts/sec/chip",
            "vs_baseline": (round(ok[best]["pts_per_sec"] / ref, 3)
                            if ref else None),
            "precision": {k: {kk: (round(vv, 6) if isinstance(vv, float)
                                   else vv) for kk, vv in v.items()}
                          for k, v in out.items()},
        }
    elif args.minimax:
        payload = bench_minimax(n_f, nx, nt, widths, n_steps)
    elif args.scale:
        # stream a payload line per completed point: if a later, larger
        # point hangs past the supervisor timeout, the salvage path in
        # run_worker still recovers everything measured so far
        def on_point(partial):
            p = scale_payload(partial)
            if p is not None:
                print(json.dumps(p), flush=True)

        out = bench_scale(nx, nt, widths, n_steps, on_point=on_point,
                          fused=engine_hint())
        payload = scale_payload(out)
        if payload is None:
            raise RuntimeError(f"all scale points failed: {out}")
    elif args.remat:
        # VERDICT r4 #4 tail: MEASURE the remat (jax.checkpoint) HBM-for-
        # FLOPs trade instead of asserting it.  Same SA step, remat off vs
        # on, at the headline size and the reference's multi-GPU size —
        # neither OOMs on a v5e (the scale sweep proved the capacity), so
        # this row prices the lever for when a larger N_f or a smaller
        # chip does need it.
        sizes = [2048] if fast else [50_000, 500_000]
        out = {}
        for nf_pt in sizes:
            steps = max(10, n_steps * sizes[0] // nf_pt)
            for rm in (False, True):
                key = f"{nf_pt}" + ("+remat" if rm else "")
                try:
                    r = bench_jax_throughput(nf_pt, nx, nt, widths, steps,
                                             fused=engine_hint(), remat=rm)
                    out[key] = {
                        "pts_per_sec": round(r["pts_per_sec_per_chip"]),
                        "engine": r["engine"],
                        "mfu": (round(r["mfu"], 4)
                                if r["mfu"] is not None else None)}
                except Exception as e:
                    out[key] = {"error": f"{type(e).__name__}: {e}"}
                    log(f"[remat] {key} FAILED: {out[key]['error']}")
                # stream per-point (like --scale): a timeout at the 500k
                # points must not discard the measurements already taken
                partial = remat_payload(out)
                if partial is not None:
                    print(json.dumps(partial), flush=True)
        payload = remat_payload(out)
        if payload is None:
            raise RuntimeError(f"all remat points failed: {out}")
    elif args.serving:
        # stream per-phase like --scale: a timeout in the coalesced-query
        # phase still salvages the dense-grid rates
        def on_phase(partial):
            import jax
            partial.setdefault("backend", jax.default_backend())
            partial.setdefault("device_kind", jax.devices()[0].device_kind)
            print(json.dumps(partial), flush=True)

        payload = bench_serving(n_f, nx, nt, widths, on_phase=on_phase)
    elif args.fleet:
        # stream per-phase like --serving: a timeout in the QPS grid
        # still salvages the warm-start measurement
        def on_phase(partial):
            import jax
            partial.setdefault("backend", jax.default_backend())
            partial.setdefault("device_kind", jax.devices()[0].device_kind)
            print(json.dumps(partial), flush=True)

        payload = bench_fleet(n_f, nx, nt, widths, on_phase=on_phase)
    elif args.obs:
        # stream per-phase like --fleet: a timeout in the observed phase
        # still salvages the bare-baseline measurement
        def on_phase(partial):
            import jax
            partial.setdefault("backend", jax.default_backend())
            partial.setdefault("device_kind", jax.devices()[0].device_kind)
            print(json.dumps(partial), flush=True)

        o_nf = 256 if fast else 2048
        o_widths = [16, 16] if fast else [64, 64]
        payload = bench_obs(o_nf, 64 if fast else 512,
                            16 if fast else 201, o_widths,
                            on_phase=on_phase)
    elif args.closedloop:
        # stream per-phase like --fleet: a timeout in the retrain/swap
        # phase still salvages the detection-latency measurement
        def on_phase(partial):
            import jax
            partial.setdefault("backend", jax.default_backend())
            partial.setdefault("device_kind", jax.devices()[0].device_kind)
            print(json.dumps(partial), flush=True)

        cl_nf = 256 if fast else 2048
        cl_widths = [16, 16] if fast else [64, 64]
        payload = bench_closedloop(cl_nf, 64 if fast else 512,
                                   16 if fast else 201, cl_widths,
                                   on_phase=on_phase)
    elif args.factory:
        f_nf = 256 if fast else 2048
        f_widths = [16, 16] if fast else [64, 64]
        f_steps = 30 if fast else 200
        payload = bench_factory(f_nf, 64 if fast else 512,
                                16 if fast else 201, f_widths, f_steps,
                                n_members=64)
    elif args.resample:
        # stream a payload line per completed arm (like --scale's
        # per-point lines): a timeout in the third arm still salvages
        # the finished arms as a disclosed partial.  The fast config is
        # the measured separation point on the CI host (N_f=2048 seed 0:
        # fixed-LHS plateaus ~1.4e-1 under L-BFGS while the resampled
        # arm refines through the 1.2e-1 gate); the full config is the
        # 3-seed ablation's (runs/resample_ablation.json) with its 5e-2
        # convergence gate.
        r_nf = 2_048 if fast else 5_000
        r_widths = [20, 20, 20, 20]
        r_adam = 2_000 if fast else 3_000
        r_newton = 2_000
        r_every = 500
        r_eval = 250 if fast else 500
        r_gate = 0.12 if fast else 0.05

        def on_arm(arms):
            partial = resample_payload(arms, gate=r_gate, n_f=r_nf,
                                       budget=r_adam + r_newton,
                                       resample_every=r_every)
            if partial is not None:
                import jax
                partial.setdefault("backend", jax.default_backend())
                partial.setdefault("device_kind",
                                   jax.devices()[0].device_kind)
                print(json.dumps(partial), flush=True)

        payload = bench_resample(r_nf, r_widths, r_adam, r_newton,
                                 r_every, r_eval, r_gate, on_arm=on_arm)
    elif args.zoo:
        # the PDE-zoo scorecard (tensordiffeq_tpu/zoo/): race the three
        # adaptive arms per registered entry at its declared (budget,
        # gate), streaming the card-so-far after every completed entry so
        # a timeout salvages a disclosed subset.  BENCH_ZOO_ENTRIES
        # (comma-separated ids) selects a subset, BENCH_ZOO_SIZE picks
        # the declared operating point, and BENCH_ZOO_CAP (or BENCH_FAST)
        # caps each optimizer phase — capped cards say so and the diff
        # gate skips their gate comparison.
        from tensordiffeq_tpu import zoo as tdq_zoo
        z_ids = [s for s in
                 os.environ.get("BENCH_ZOO_ENTRIES", "").split(",")
                 if s] or None
        z_size = os.environ.get("BENCH_ZOO_SIZE", "micro")
        z_cap = (int(os.environ["BENCH_ZOO_CAP"])
                 if "BENCH_ZOO_CAP" in os.environ
                 else (60 if fast else None))

        def zoo_payload(card):
            done = card["entries"]
            return {
                "metric": f"PDE-zoo scorecard ({z_size}): "
                          "entries gated (any arm)",
                "value": sum(1 for e in done.values()
                             if any(a["gated"]
                                    for a in e["arms"].values())),
                "unit": "entries",
                "vs_baseline": None,
                "entries_run": len(done),
                "systems": sum(1 for e in done.values() if e["system"]),
                "arms_gated": sum(1 for e in done.values()
                                  for a in e["arms"].values()
                                  if a["gated"]),
                "scorecard": card,
            }

        def on_entry(card):
            print(json.dumps(zoo_payload(card)), flush=True)

        card = tdq_zoo.run_scorecard(z_ids, z_size, budget_cap=z_cap,
                                     on_entry=on_entry)
        payload = zoo_payload(card)
    elif args.full:
        def full_payload(r):
            p = {"metric":
                 "AC-SA wall-clock (10k Adam + 10k L-BFGS) w/ rel-L2",
                 "value": round(r["wall"], 2), "unit": "s",
                 "vs_baseline": r["l2"], "rel_l2": r["l2"],
                 "time_to_l2_2.1e-2": r["t_target"],
                 "engine": r.get("engine"),
                 "windows": r.get("windows", 1),
                 "timeline": r["timeline"]}
            return p

        def on_eval(snap):
            # stream a salvageable snapshot per evaluation (backend tag
            # added here because the salvage path never reaches the
            # setdefault at the bottom of worker_main)
            import jax
            p = full_payload(snap)
            p["backend"] = jax.default_backend()
            p["device_kind"] = jax.devices()[0].device_kind
            print(json.dumps(p), flush=True)

        res = bench_time_to_l2(
            n_f, nx, nt, widths,
            adam_iter=100 if fast else 10_000,
            newton_iter=100 if fast else 10_000,
            eval_every=50 if fast else 1_000,
            on_eval=on_eval, fused=engine_hint())
        payload = full_payload(res)
    else:
        hint_fused = engine_hint()
        p_fused, p_dtype, p_mm = precision_hint()
        if p_dtype is not None:
            hint_fused = p_fused  # the bf16 config carries its own engine
        r = bench_jax_throughput(n_f, nx, nt, widths, n_steps,
                                 fused=hint_fused, fused_dtype=p_dtype,
                                 minimax=p_mm)
        base = get_baseline(n_f, nx, widths, max(3, n_steps // 10))
        payload = {
            "metric": "AC SA-PINN training throughput (full minimax step)",
            "value": round(r["pts_per_sec_per_chip"]),
            "unit": "collocation-pts/sec/chip",
            "vs_baseline": (round(r["pts_per_sec_per_chip"] / base, 3)
                            if base else None),
            "mfu": (round(r["mfu"], 4) if r["mfu"] is not None else None),
            "flops_per_step": r["flops_per_step"],
            "device_kind": r["device_kind"],
            "backend": r["backend"],
            "engine": r["engine"],
        }
        # note only when the bf16 hint actually survived (not fallen back)
        if p_dtype is not None and p_dtype in r["engine"]:
            payload["precision_note"] = (
                "mixed-precision fused engine (bf16 matmul operands, f32 "
                "accumulation) — measured-best in BENCH_TPU_precision.json; "
                "accuracy-validated end-to-end (runs/bf16_accuracy.json)")
    # every mode records what it actually ran on: jax can fall back to CPU
    # without erroring, and promotion scripts gate on backend == "tpu";
    # "captured" dates the measurement even when artifact mtimes are reset
    import jax
    payload.setdefault("backend", jax.default_backend())
    payload.setdefault("device_kind", jax.devices()[0].device_kind)
    payload.setdefault("captured", time.strftime("%Y-%m-%d"))
    if chaos is not None:
        # what was injected and what actually fired: the denominator for
        # the recovery-overhead read of the telemetry block below
        payload["chaos"] = {"spec": chaos.spec(),
                            "fired": dict(chaos.fired)}
    try:
        payload.setdefault("telemetry", bench_telemetry_block())
    except Exception as e:  # observability must never cost a measurement
        log(f"[telemetry] snapshot failed: {type(e).__name__}: {e}")
    print(json.dumps(payload), flush=True)


ELASTIC_WORKER = '''
import os, sys
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
ckpt, tf_iter = sys.argv[4], int(sys.argv[5])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
from tensordiffeq_tpu.parallel import initialize_multihost
initialize_multihost(f"127.0.0.1:{port}", nproc, pid)
import numpy as np
from tensordiffeq_tpu import CollocationSolverND, DomainND, grad
from tensordiffeq_tpu.resilience import (Preempted, PreemptionHandler,
                                         auto_resume, handle_preemption)

domain = DomainND(["x", "t"], time_var="t")
domain.add("x", [-1.0, 1.0], 16)
domain.add("t", [0.0, 1.0], 8)
domain.generate_collocation_points(1024, seed=3)

def f_model(u, x, t):
    return grad(u, "t")(x, t) - 0.05 * grad(grad(u, "x"), "x")(x, t)

solver = CollocationSolverND(verbose=False)
solver.compile([2, 16, 16, 1], f_model, domain, [], dist=True, fused=False)
with PreemptionHandler(deadline_s=30):
    try:
        auto_resume(solver, ckpt, tf_iter=tf_iter, checkpoint_every=5,
                    chunk=5)
    except Preempted as e:
        handle_preemption(e)
tl = [d["Total Loss"] for d in solver.losses]
assert all(np.isfinite(v) for v in tl), tl
if pid == 0:
    print("FINAL_LOSS %.8e" % tl[-1], flush=True)
jax.distributed.shutdown()
'''


def bench_elastic():
    """``--elastic``: the recovery SLO of the elastic multi-host path,
    measured end-to-end on a REAL 2-process gloo cluster (CPU backend, 4
    virtual devices per host — the same code path a pod runs over DCN):

    * a chaos ``host_loss_at`` hard-kills host 1 mid-run, right after
      the epoch-10 checkpoint;
    * the :class:`~tensordiffeq_tpu.resilience.ClusterSupervisor`
      detects the exit, drains the hung survivor, and relaunches ONE
      worker whose restore re-shards the 8-device checkpoint onto its 4
      local devices;
    * headline ``value`` = recovery wall time (loss detection -> first
      post-resume heartbeat, i.e. restore + re-shard + recompile +
      first chunk), plus ``post_resume_throughput_delta`` (epochs/s on
      the surviving half-topology vs the full one, from the supervisor's
      heartbeat progress samples).

    Runs in the driver process (it only spawns subprocesses; no
    accelerator needed or used) and never touches the TPU cache."""
    import tempfile

    from tensordiffeq_tpu.resilience import ClusterSupervisor, HostLost

    chaos_spec = "host_loss_at=10"
    tf_iter = int(os.environ.get("BENCH_ELASTIC_EPOCHS", "20"))
    work = tempfile.mkdtemp(prefix="tdq_elastic_bench_")
    script = os.path.join(work, "worker.py")
    with open(script, "w") as fh:
        fh.write(ELASTIC_WORKER)
    ckpt = os.path.join(work, "ck")
    env = {"PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
           "PALLAS_AXON_POOL_IPS": "", "TDQ_CHAOS": chaos_spec}

    def worker_cmd(pid, nproc, port):
        return [sys.executable, script, str(pid), str(nproc), str(port),
                ckpt, str(tf_iter)]

    payload = {
        "metric": "elastic recovery: 2-host cluster, host loss mid-run",
        "value": None, "unit": "s (host-loss detect -> resumed progress)",
        "vs_baseline": None, "chaos": chaos_spec, "tf_iter": tf_iter,
    }
    t0 = time.time()
    sup = ClusterSupervisor(worker_cmd, nproc=2, workdir=work,
                            heartbeat_timeout_s=180, grace_s=5.0,
                            max_relaunches=2, env=env)
    try:
        result = sup.run(timeout_s=float(os.environ.get(
            "BENCH_ELASTIC_TIMEOUT", "420")))
    except HostLost as e:
        payload["error"] = f"HostLost: {e}"
        return payload
    payload["wall_s"] = round(time.time() - t0, 3)
    payload["hosts_lost"] = result.hosts_lost
    payload["relaunches"] = result.relaunches
    payload["recovered"] = result.ok
    if result.recovery_wall_s:
        payload["value"] = round(result.recovery_wall_s[0], 3)
    gens = [{"nproc": g.nproc, "wall_s": round(g.wall_s, 3),
             "returncodes": g.returncodes,
             "lost": [list(l) for l in g.lost],
             "first_beat_s": (None if g.first_beat_s is None
                              else round(g.first_beat_s, 3)),
             "epochs_per_s": (None if g.epochs_per_s is None
                              else round(g.epochs_per_s, 4))}
            for g in result.generations]
    payload["generations"] = gens
    thr = [g["epochs_per_s"] for g in gens]
    if len(thr) >= 2 and thr[0] and thr[-1]:
        # surviving-topology throughput vs pre-loss (expected < 0: half
        # the devices); disclosed, not hidden, so SLO math can price the
        # degraded window
        payload["post_resume_throughput_delta"] = \
            round(thr[-1] / thr[0] - 1.0, 4)
    else:
        payload["post_resume_throughput_delta"] = None
    final = None
    try:
        # worker 0 of whichever generation finished the job (the chaos
        # kill normally costs exactly one relaunch, but a clean run or a
        # double relaunch put FINAL_LOSS in a different generation's log)
        last_gen = result.generations[-1].generation
        with open(os.path.join(work, f"gen{last_gen}.worker0.out")) as fh:
            for ln in fh:
                if ln.startswith("FINAL_LOSS"):
                    final = float(ln.split()[1])
    except OSError:
        pass
    payload["final_loss"] = final
    log(f"[elastic] recovered={result.ok} recovery="
        f"{payload['value']}s throughput_delta="
        f"{payload['post_resume_throughput_delta']} final_loss={final}")
    return payload


FLEETHA_BOOTSTRAP = '''\
"""Replica bootstrap for bench.py --mode fleetha (written to a temp dir
and imported by each replica worker via --bootstrap)."""
import os

from tensordiffeq_tpu import grad
from tensordiffeq_tpu.fleet import FleetRouter, TenantPolicy

ART = {arts!r}


def f_model(u, x, t):
    u_xx = grad(grad(u, "x"), "x")
    u_t = grad(u, "t")
    uv = u(x, t)
    return u_t(x, t) - {eps!r} * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv


def make_router():
    router = FleetRouter(max_loaded=4)
    for name in ("t0", "t1"):
        router.register(
            name, os.path.join(ART, name),
            policy=TenantPolicy(min_bucket={min_b}, max_bucket={max_b},
                                max_batch=256, max_latency_s=0.005),
            f_model=f_model)
    return router
'''


def _fleetha_compiles(run_dir):
    """Sum of ``serving.engine.compiles*`` counters in a replica run
    dir's live metrics snapshot (written atomically at every beat, last
    of them right before exit)."""
    from tensordiffeq_tpu.telemetry.collector import SNAPSHOT_FILE
    try:
        with open(os.path.join(run_dir, SNAPSHOT_FILE)) as fh:
            counters = (json.load(fh).get("metrics") or {}).get(
                "counters") or {}
    except (OSError, ValueError):
        return None
    return sum(v for k, v in counters.items()
               if k.startswith("serving.engine.compiles"))


def bench_fleetha():
    """``--mode fleetha``: the replicated-serving failover drill,
    end-to-end on a REAL 2-replica group (separate processes, stdlib
    HTTP, CPU jax):

    * two tiny fleet artifacts (tenants t0/t1) export in the driver and
      warm-start in every replica;
    * a chaos ``host_loss_at`` hard-kills replica 1 at its Nth request —
      mid-traffic, connections dropped, no drain;
    * the :class:`~tensordiffeq_tpu.fleet.FrontRouter` fails the dropped
      requests over (breaker + rendezvous rehash) while the serving-mode
      :class:`~tensordiffeq_tpu.resilience.ClusterSupervisor` respawns
      the slot warm from the shared artifacts;
    * headline ``value`` = query p99 across the whole incident;
      ``requests_lost`` MUST be 0 (no query the front tier gave up on),
      ``request_time_compiles_survivor`` MUST be 0 (the survivor absorbs
      the rerouted tenants without a single request-time compile).

    Driver-process mode like ``--elastic``: spawns its own CPU
    subprocesses, no accelerator probe, no TPU cache."""
    import tempfile

    from tensordiffeq_tpu import fleet
    from tensordiffeq_tpu.fleet.replica import (FrontRouter, ReplicaGroup,
                                                ReplicaUnavailable)
    from tensordiffeq_tpu.telemetry import default_registry

    fast = os.environ.get("BENCH_FAST") == "1"
    n_queries = 40 if fast else 200
    loss_at = max(5, n_queries // 4)
    min_b, max_b = 64, 128
    chaos_spec = f"host_loss_at={loss_at},host_loss_rank=1"

    work = tempfile.mkdtemp(prefix="tdq_fleetha_bench_")
    arts = os.path.join(work, "artifacts")
    for i in range(2):
        solver = build_solver(64, 16, 8, [8, 8], seed=i)
        fleet.export_fleet_artifact(
            solver.export_surrogate(), os.path.join(arts, f"t{i}"),
            min_bucket=min_b, max_bucket=max_b)
    boot_dir = os.path.join(work, "boot")
    os.makedirs(boot_dir, exist_ok=True)
    with open(os.path.join(boot_dir, "tdq_fleetha_boot.py"), "w") as fh:
        fh.write(FLEETHA_BOOTSTRAP.format(arts=arts, eps=EPS,
                                          min_b=min_b, max_b=max_b))

    repo = os.path.dirname(os.path.abspath(__file__))
    env = {"PYTHONPATH": boot_dir + os.pathsep + repo,
           "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
           "TDQ_CHAOS": chaos_spec}
    payload = {
        "metric": "replicated serving failover: 2 replicas, "
                  "host loss mid-traffic",
        "value": None, "unit": "s (query p99 across the incident)",
        "vs_baseline": None, "chaos": chaos_spec,
        "requests_total": n_queries,
    }
    budget = float(os.environ.get("BENCH_BUDGET", "900"))
    t0_all = time.time()
    group = ReplicaGroup("tdq_fleetha_boot:make_router", nproc=2,
                         workdir=os.path.join(work, "replicas"),
                         heartbeat_timeout_s=180.0, max_relaunches=2,
                         env=env)
    group.start(timeout_s=budget)
    group.wait_ready(timeout_s=min(300.0, budget))
    survivor_dir = os.path.join(group.workdir, "replica0.gen0")
    survivor_base = _fleetha_compiles(survivor_dir)
    front = FrontRouter(group.endpoints(), deadline_s=30.0,
                        breaker_reset_timeout_s=2.0)

    rng = np.random.RandomState(0)
    lat, lost, avail_min = [], 0, 1.0
    for i in range(n_queries):
        X = np.stack([rng.uniform(-1.0, 1.0, min_b),
                      rng.uniform(0.0, 1.0, min_b)], -1).astype(np.float32)
        t0 = time.time()
        try:
            front.query(f"t{i % 2}", X, kind="u" if i % 3 else "residual")
        except ReplicaUnavailable:
            lost += 1
        lat.append(time.time() - t0)
        avail_min = min(avail_min, front.availability())
    # the respawned slot must come back WARM before the goodbye — its
    # first beat is what closes the supervisor's recovery-wall clock
    group.wait_ready(timeout_s=min(300.0, budget))
    result = group.shutdown(timeout_s=120.0)

    lat_sorted = sorted(lat)
    p99 = lat_sorted[min(len(lat) - 1, int(0.99 * len(lat)))]
    payload["value"] = round(p99, 4)
    payload["failover_max_s"] = round(lat_sorted[-1], 4)
    payload["median_s"] = round(lat_sorted[len(lat) // 2], 6)
    payload["requests_lost"] = lost
    payload["availability_min"] = round(avail_min, 3)
    payload["hosts_lost"] = result.hosts_lost
    payload["relaunches"] = result.relaunches
    payload["recovery_wall_s"] = (round(result.recovery_wall_s[0], 3)
                                  if result.recovery_wall_s else None)
    ctr = default_registry().as_dict()["counters"]
    payload["reroutes"] = int(ctr.get("fleet.failover.reroutes", 0))
    payload["failover_attempts"] = sum(
        v for k, v in ctr.items()
        if k.startswith("fleet.failover.attempts"))
    survivor_final = _fleetha_compiles(survivor_dir)
    payload["request_time_compiles_survivor"] = (
        None if survivor_base is None or survivor_final is None
        else int(survivor_final - survivor_base))
    payload["wall_s"] = round(time.time() - t0_all, 3)
    log(f"[fleetha] lost={lost}/{n_queries} p99={p99 * 1e3:.1f}ms "
        f"reroutes={payload['reroutes']} recovery="
        f"{payload['recovery_wall_s']}s survivor_compiles="
        f"{payload['request_time_compiles_survivor']}")
    return payload


def lint_verdict():
    """``bench.py --lint`` body: the tdqlint AST pass over the package +
    bench.py (tensordiffeq_tpu.analysis), as a machine-readable verdict
    dict; the caller turns ``ok`` into the exit code."""
    from tensordiffeq_tpu.analysis import run_analysis
    findings, modules = run_analysis()
    return {"metric": "tdqlint static analysis (AST rules)",
            "ok": not findings, "value": len(findings), "unit": "findings",
            "files_scanned": len(modules),
            "findings": [f.format() for f in findings]}


def zoo_diff_verdict(target, baseline_path=None):
    """``bench.py --zoo-diff`` body: hold a fresh scorecard (a ``--zoo``
    payload JSON or a bare scorecard document) to the checked-in
    ``SCORECARD.json`` baseline via
    :func:`tensordiffeq_tpu.zoo.diff_scorecards`.  Returns the verdict
    dict; the caller turns ``ok`` into the exit code (3 on regression)."""
    from tensordiffeq_tpu.zoo import diff_scorecards
    base = baseline_path or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "SCORECARD.json")
    with open(base) as fh:
        baseline = json.load(fh)
    with open(target) as fh:
        current = json.load(fh)
    verdict = {"metric": "PDE-zoo scorecard diff vs checked-in baseline",
               **diff_scorecards(baseline, current)}
    verdict["value"] = len(verdict["regressions"])
    verdict["unit"] = "regressions"
    verdict["baseline"] = base
    return verdict


def slo_verdict(target):
    """``bench.py --slo`` body: the default
    :class:`tensordiffeq_tpu.telemetry.SLOSet` verdict for ``target`` — a
    telemetry run directory (manifest metrics + events, including the
    step-time-regression window) or any bench payload JSON file (its
    embedded ``telemetry.metrics`` registry snapshot).  Returns the
    verdict dict; the caller turns ``ok`` into the exit code."""
    from tensordiffeq_tpu.telemetry.slo import SLOSet
    slo = SLOSet.default()
    if os.path.isdir(target):
        verdict = slo.evaluate_run(target)
        source = "run_dir"
    else:
        payload = last_json_line(open(target).read())
        if payload is None:
            raise ValueError(f"no JSON payload line in {target}")
        metrics = ((payload.get("telemetry") or {}).get("metrics")) or {}
        verdict = slo.evaluate(metrics)
        source = "payload"
    return dict(verdict, target=str(target), source=source)


def last_json_line(text):
    """Last parseable JSON-object line of a worker's stdout (bytes or str).

    Workers stream one payload line per completed measurement, so this is
    both the normal result path and the partial-salvage path."""
    if isinstance(text, bytes):
        text = text.decode("utf-8", "replace")
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def probe_backend(timeout):
    """Subprocess probe: which JAX backend initializes within ``timeout``?

    Returns the backend name ("tpu"/"cpu"/...) or None on hang/crash.  This
    is the 2-minute answer to the round-2 failure mode where backend init
    hung for the worker's entire 1500 s budget (BENCH_r02.json)."""
    code = "import jax; jax.devices(); print(jax.default_backend())"
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        log(f"[probe] backend init hung >{timeout:.0f}s")
        return None
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        log(f"[probe] backend init failed: {' | '.join(tail)}")
        return None
    out = (proc.stdout or "").strip().splitlines()
    backend = out[-1] if out else None
    log(f"[probe] backend = {backend}")
    return backend


def mode_name(mode_flags):
    return mode_flags[0].lstrip("-") if mode_flags else "default"


def tpu_cache_file(mode_flags):
    return os.path.join(TPU_CACHE_DIR,
                        f"BENCH_TPU_{mode_name(mode_flags)}.json")


def adopt_best_validated(cached):
    """Default-mode cached emission quotes the measured-best
    accuracy-VALIDATED config of the SAME metric (full SA minimax step,
    same config/chip) when the promoted precision sweep beats the cached
    default capture — the round-4 promotion rule ("the headline must be
    the measured best") applied at emission time.  2026-08-01: the
    default capture ran f32-pallas (8.98M pts/s) minutes before the
    precision sweep measured bf16-pallas at 17.87M on the same chip; a
    cached emission must not hide the 2× that is already on record.
    Mutates ``cached`` in place; provenance in ``adopted_from``."""
    try:
        prec = load_cached_tpu(["--precision"])
        info = (prec or {}).get("precision", {})
        validated = {k: info[k] for k in ("bf16-pallas", "bf16-taylor")
                     if isinstance(info.get(k), dict)
                     and isinstance(info[k].get("pts_per_sec"), (int, float))}
        if not validated:
            return
        best = max(validated, key=lambda k: validated[k]["pts_per_sec"])
        row = validated[best]
        old = cached.get("value")
        if not isinstance(old, (int, float)) or row["pts_per_sec"] <= old:
            return
        if isinstance(cached.get("vs_baseline"), (int, float)) and old:
            cached["vs_baseline"] = round(
                cached["vs_baseline"] * row["pts_per_sec"] / old, 3)
        cached["value"] = round(row["pts_per_sec"])
        cached["engine"] = best
        for field in ("mfu", "flops_basis", "mfu_note"):
            if field in row:
                cached[field] = row[field]
            else:
                cached.pop(field, None)
        cached.pop("flops_per_step", None)
        cached["adopted_from"] = (
            f"BENCH_TPU_precision.json ({prec.get('captured', '?')}): "
            f"measured-best validated config {best!r} beats the cached "
            "default capture on the same step/config/chip")
    except Exception as e:
        log(f"[cached] adopt_best_validated skipped: {type(e).__name__}: {e}")


def load_cached_tpu(mode_flags):
    """Last-good on-hardware payload for this mode, tagged as cached, or
    None.  Only real-TPU artifacts are ever stored here (same gate as
    scripts/_promote.sh), but re-check to be safe."""
    path = tpu_cache_file(mode_flags)
    if not os.path.exists(path):
        return None
    try:
        payload = last_json_line(open(path).read())
    except OSError:
        return None
    if not payload or payload.get("backend") != "tpu" \
            or "backend_note" in payload:
        return None
    # prefer the capture date stored in the payload — file mtime is reset
    # by checkouts/copies and would stamp an old measurement as fresh
    day = payload.get("captured") or time.strftime(
        "%Y-%m-%d", time.gmtime(os.path.getmtime(path)))
    payload["backend_note"] = f"tpu-cached-{day}"
    return payload


def save_tpu_cache(mode_flags, payload):
    """Persist a live hardware payload as the mode's last-good artifact —
    the same acceptance rule as scripts/_promote.sh: real TPU backend, no
    fallback sentinel, and a partial sweep never replaces a complete one."""
    if payload.get("backend") != "tpu" or "backend_note" in payload:
        return
    # Partial sweeps are never cached here: seeding one would trip the
    # watcher's [ -s BENCH_TPU_<m>.json ] idempotency guards and block the
    # complete run forever.  Partials still reach artifacts through
    # scripts/_promote.sh, whose gap-filling rule the watcher understands.
    if "partial" in payload:
        return
    path = tpu_cache_file(mode_flags)
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(
                dict(payload, captured=time.strftime("%Y-%m-%d"))) + "\n")
        os.replace(tmp, path)
        log(f"[supervisor] cached hardware payload -> {path}")
    except OSError as e:
        log(f"[supervisor] cache write failed: {e}")


def cache_age_days(payload):
    """Days since the cached payload's on-hardware capture date, or None."""
    cap = payload.get("captured")
    if not cap:
        return None
    try:
        then = time.mktime(time.strptime(cap, "%Y-%m-%d"))
    except ValueError:
        return None
    return round((time.time() - then) / 86400, 1)


def probe_failure_streak():
    """Consecutive failed tunnel probes ending at the most recent one, from
    the watcher's probe-by-probe record (runs/tunnel_history.log) — together
    with ``cache_age_days`` this tells the driver at a glance how stale a
    cached hardware number is and how long the tunnel has been dark."""
    path = os.path.join(REPO, "runs", "tunnel_history.log")
    try:
        with open(path) as fh:
            lines = fh.read().splitlines()
    except OSError:
        return None
    n = 0
    for ln in reversed(lines):
        if "unhealthy" in ln:
            n += 1
        else:
            break
    return n


def cpu_sanity(timeout):
    """Fresh small CPU measurement (BENCH_FAST config) to attach alongside a
    cached hardware payload — proves the code still runs end-to-end today
    even when the tunnel doesn't."""
    # BENCH_ENGINE cleared: a TPU-oriented override (e.g. pallas) must
    # never reach a CPU worker, where it would run in interpret mode
    env = dict(os.environ, BENCH_FAST="1", JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="", BENCH_ENGINE="")
    payload, err = run_worker(["--force-cpu"], timeout, env=env)
    if payload is None:
        return {"error": err}
    return {k: payload.get(k) for k in
            ("metric", "value", "unit", "backend", "loss")
            if k in payload}


def run_worker(flags, timeout, env=None):
    cmd = [sys.executable, os.path.abspath(__file__), "--worker"] + flags
    log(f"[supervisor] running {' '.join(cmd)} (timeout {timeout:.0f}s)")
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, cwd=REPO, env=env)
    except subprocess.TimeoutExpired as e:
        # surface where the worker was when killed — without this the
        # difference between "tunnel died mid-run" and "budget too small"
        # is invisible (round-3 step-1 diagnosis)
        if e.stderr:
            tail = e.stderr if isinstance(e.stderr, str) \
                else e.stderr.decode("utf-8", "replace")
            sys.stderr.write("[supervisor] worker stderr tail at timeout:\n"
                             + tail[-2000:] + "\n")
        # salvage streamed partial payloads (e.g. --scale prints one line
        # per completed sweep point) before declaring the attempt dead
        payload = last_json_line(e.stdout)
        if payload is not None:
            payload["partial"] = ("worker timed out after this "
                                  "measurement; later points lost")
            payload.setdefault("captured", time.strftime("%Y-%m-%d"))
            return payload, None
        return None, "worker timed out (backend init hang or slow compile)"
    sys.stderr.write(proc.stderr[-4000:] if proc.stderr else "")
    if proc.returncode != 0:
        # a worker that crashed mid-sweep (OOM/segfault on a later point)
        # still streamed every completed measurement — salvage like timeout
        payload = last_json_line(proc.stdout)
        if payload is not None:
            payload["partial"] = (f"worker died (rc={proc.returncode}) "
                                  "after this measurement; later points lost")
            payload.setdefault("captured", time.strftime("%Y-%m-%d"))
            return payload, None
        tail = (proc.stderr or "").strip().splitlines()[-8:]
        return None, f"worker rc={proc.returncode}: " + " | ".join(tail)
    payload = last_json_line(proc.stdout)
    if payload is not None:
        return payload, None
    return None, "worker produced no JSON line"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="train AC-SA to convergence and report time-to-L2")
    ap.add_argument("--engines", action="store_true",
                    help="compare generic / fused-xla / fused-pallas "
                         "residual engines on the SA train step")
    ap.add_argument("--precision", action="store_true",
                    help="compare f32-HIGHEST / f32-default / bf16 network "
                         "configs (incl. the fused-minimax rows)")
    ap.add_argument("--minimax", action="store_true",
                    help="price the fused minimax step (residual + SA-λ "
                         "loss + cotangents + λ-ascent as one fusion) "
                         "against the unfused fused-XLA path")
    ap.add_argument("--scale", action="store_true",
                    help="single-chip throughput sweep over N_f up to 500k "
                         "(the reference's multi-GPU config)")
    ap.add_argument("--remat", action="store_true",
                    help="price the remat (jax.checkpoint) HBM-for-FLOPs "
                         "trade: SA step with remat off vs on")
    ap.add_argument("--serving", action="store_true",
                    help="batched surrogate inference: dense-grid u/residual "
                         "rates + coalesced-query QPS through the serving "
                         "subsystem")
    ap.add_argument("--fleet", action="store_true",
                    help="multi-tenant fleet serving: cold vs AOT-warm-start "
                         "first-query latency + N-tenant mixed u/residual "
                         "QPS through the fleet router")
    ap.add_argument("--resample", action="store_true",
                    help="adaptive-collocation race on Burgers: "
                         "steps-to-rel-L2-gate for fixed LHS vs adaptive "
                         "(host path) vs adaptive+device-resident "
                         "pipelined redraw, plus the per-redraw "
                         "host-visible stall split")
    ap.add_argument("--factory", action="store_true",
                    help="surrogate-factory race: aggregate training "
                         "throughput of a 64-member coefficient-sweep "
                         "family as ONE vmapped program vs the same "
                         "members trained sequentially")
    ap.add_argument("--closedloop", action="store_true",
                    help="the autonomous closed loop end to end: serve a "
                         "surrogate family, inject parameter drift, and "
                         "measure detection latency, retrain wall, swap "
                         "cutover stall p50 and post-swap residual "
                         "improvement through DriftMonitor / "
                         "RetrainController / FleetRouter.hot_swap")
    ap.add_argument("--obs", action="store_true",
                    help="price the observability plane: the same "
                         "multi-tenant traffic bare vs fully observed "
                         "(span tracer into a rotating run log, flight-"
                         "recorder ring, collector serving /metrics + "
                         "/healthz scraped during traffic), with the "
                         "bare run-to-run noise band disclosed")
    ap.add_argument("--zoo", action="store_true",
                    help="PDE-zoo scorecard: race the three adaptive "
                         "arms (fixed LHS / pool top-k / PACMANN ascent) "
                         "over the registered entries at their declared "
                         "(budget, gate) and emit one machine-readable "
                         "scorecard (see tensordiffeq_tpu/zoo/ and "
                         "SCORECARD.json)")
    ap.add_argument("--zoo-diff", metavar="TARGET",
                    help="CI gate, not a measurement: diff a scorecard "
                         "JSON (bench --zoo payload or bare scorecard) "
                         "against the checked-in SCORECARD.json baseline "
                         "and exit 3 on a gated-entry regression or a "
                         "fused-engine downgrade (like --slo/--lint, "
                         "exempt from the exit-0-always contract)")
    ap.add_argument("--zoo-baseline", metavar="PATH",
                    help="override the baseline scorecard for --zoo-diff "
                         "(default: SCORECARD.json next to bench.py)")
    ap.add_argument("--mode", choices=["default", "full", "engines",
                                       "precision", "minimax", "scale",
                                       "remat", "serving", "fleet",
                                       "resample", "factory",
                                       "closedloop", "zoo", "obs",
                                       "fleetha"],
                    help="alternative spelling of the mode flags: "
                         "--mode serving == --serving")
    ap.add_argument("--slo", metavar="TARGET",
                    help="evaluate the default SLO set against an existing "
                         "runs/<dir> or bench payload JSON and exit nonzero "
                         "on breach (machine-readable verdict line; a CI "
                         "gate, not a measurement mode)")
    ap.add_argument("--lint", action="store_true",
                    help="run the tdqlint static-analysis gate (AST rules "
                         "over the package + bench.py; see "
                         "tensordiffeq_tpu/analysis/) and exit nonzero on "
                         "findings — a CI gate, not a measurement mode; "
                         "like --slo it is exempt from the exit-0-always "
                         "contract")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic recovery SLO: run a real 2-process gloo "
                         "cluster, hard-kill one host via chaos "
                         "host_loss_at, and report the supervisor's "
                         "recovery wall time + post-resume throughput "
                         "delta (CPU-only by design; no TPU cache)")
    ap.add_argument("--fleetha", action="store_true",
                    help="replicated-serving failover drill: run a real "
                         "2-replica serving group, hard-kill one replica "
                         "via chaos host_loss_at mid-traffic, and report "
                         "failover p99 / requests lost (must be 0) / "
                         "supervisor recovery wall (CPU-only by design; "
                         "no TPU cache)")
    ap.add_argument("--chaos", metavar="SPEC",
                    help="activate deterministic fault injection for the "
                         "worker run (tensordiffeq_tpu.resilience.Chaos "
                         "spec, e.g. 'serving_fail_rate=0.2,seed=1'): "
                         "prices recovery overhead — retry/breaker/"
                         "recovery counters ride in the payload's "
                         "telemetry block.  Chaos payloads are never "
                         "promoted to the TPU scoreboard cache.")
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--force-cpu", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.mode and args.mode != "default":
        setattr(args, args.mode, True)

    if args.lint:
        # CI gate over the SOURCE: no probe, no worker, no cache — and
        # deliberately NOT exit-0-always (the finding IS the signal).
        # One machine-readable verdict line, same shape discipline as
        # --slo; the findings ride in full so CI logs are actionable.
        verdict = lint_verdict()
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 3)

    if args.slo:
        # CI gate over captured evidence: no probe, no worker, no cache —
        # and deliberately NOT exit-0-always (the breach IS the signal)
        verdict = slo_verdict(args.slo)
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 3)

    if args.zoo_diff:
        # CI gate over scorecards: no probe, no worker, no cache — and
        # deliberately NOT exit-0-always (the regression IS the signal)
        verdict = zoo_diff_verdict(args.zoo_diff, args.zoo_baseline)
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 3)

    if args.elastic:
        # driver-process mode: it spawns its own CPU cluster subprocesses
        # (no accelerator probe, no worker protocol, no TPU cache) — the
        # one-JSON-line / exit-0 contract still holds
        try:
            payload = bench_elastic()
        except Exception as e:  # noqa: BLE001 — contract: always emit
            payload = {"metric": "elastic recovery: 2-host cluster, host "
                       "loss mid-run", "value": None, "unit": None,
                       "vs_baseline": None,
                       "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(payload))
        return

    if args.fleetha:
        # driver-process mode like --elastic: spawns its own CPU replica
        # subprocesses (no accelerator probe, no worker protocol, no TPU
        # cache) — the one-JSON-line / exit-0 contract still holds
        try:
            payload = bench_fleetha()
        except Exception as e:  # noqa: BLE001 — contract: always emit
            payload = {"metric": "replicated serving failover: 2 "
                       "replicas, host loss mid-traffic", "value": None,
                       "unit": None, "vs_baseline": None,
                       "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(payload))
        return

    if args.worker:
        worker_main(args)
        return

    mode_flags = [f for f in ("--full", "--engines", "--precision",
                              "--minimax", "--scale", "--remat",
                              "--serving", "--fleet", "--resample",
                              "--factory", "--closedloop", "--zoo",
                              "--obs")
                  if getattr(args, f.lstrip("-"))]

    # Total wall budget.  The driver's no-flag invocation must finish well
    # inside its window (round 2 proved >~25 min gets killed, rc=124); the
    # explicit modes are watcher-driven with generous budgets of their own.
    default_budget = {"default": 1140, "engines": 2400, "precision": 2400,
                      "minimax": 1800, "scale": 7200, "remat": 2400,
                      "serving": 1800, "fleet": 1800, "resample": 3600,
                      "factory": 1800, "closedloop": 1800, "zoo": 7200,
                      "obs": 1800, "full": 86400}[mode_name(mode_flags)]
    budget = float(os.environ.get("BENCH_BUDGET", default_budget))
    t_start = time.time()

    def remaining():
        return budget - (time.time() - t_start)

    # per-attempt cap still honored for the watcher scripts that set it
    attempt_cap = float(os.environ.get("BENCH_TIMEOUT", budget))

    diag = []
    # chaos flags ride to the worker but never into the cache key: a
    # fault-injected measurement must not become the cached good payload
    chaos_flags = ["--chaos", args.chaos] if args.chaos else []

    backend = probe_backend(min(PROBE_TIMEOUT, max(10.0, remaining() - 30)))
    if backend and backend != "cpu":
        to = min(attempt_cap, remaining() - RESERVE_S)
        if to > 30:
            payload, err = run_worker(mode_flags + chaos_flags, to)
            if payload is not None:
                if not args.chaos:
                    save_tpu_cache(mode_flags, payload)
                if diag:
                    payload["diag"] = diag
                print(json.dumps(payload))
                return
            diag.append(err)
            log(f"[supervisor] live attempt failed: {err}")
        else:
            diag.append("no budget left for a live attempt after probe")
    else:
        diag.append(f"backend probe: {backend or 'hang/failure'}")

    # Tunnel down or live attempt failed: emit the last-good hardware
    # payload NOW — the scoreboard must never be empty when real numbers
    # exist (VERDICT r2 item 1).  The backend_note tag keeps promotion
    # scripts from mistaking this for a fresh measurement.
    # Watcher mode (BENCH_NO_CPU_FALLBACK=1): a CPU measurement can never
    # be promoted to a BENCH_TPU_* artifact, so don't burn 25+ min of a
    # flaky-tunnel window producing one — emit the cached payload (or the
    # failure sentinel) immediately and let the watcher re-probe.
    no_cpu = os.environ.get("BENCH_NO_CPU_FALLBACK") == "1"

    cached = load_cached_tpu(mode_flags)
    if cached is not None:
        if not mode_flags:
            adopt_best_validated(cached)
        age = cache_age_days(cached)
        streak = probe_failure_streak()
        cached["cache_age_days"] = age
        cached["failed_probe_streak"] = streak
        diag.append(
            ("cache age unknown (no capture date)" if age is None
             else f"cache age {age} days")
            + ("; no watcher probe record" if streak is None
               else f"; {streak} consecutive failed tunnel probes"))
        cached["diag"] = diag
        if remaining() > 240 and not no_cpu:
            cached["cpu_sanity"] = cpu_sanity(remaining() - 30)
        print(json.dumps(cached))
        return
    diag.append("no cached hardware payload for this mode")

    if no_cpu:
        print(json.dumps({"metric": mode_name(mode_flags), "value": None,
                          "unit": None, "vs_baseline": None,
                          "backend_note": "tpu-unreachable-no-cpu-fallback",
                          "diag": diag}))
        return

    log("[supervisor] falling back to CPU measurement")
    to = min(attempt_cap, remaining() - 15)
    payload = err = None
    if to > 60:
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                   BENCH_ENGINE="")
        payload, err = run_worker(mode_flags + chaos_flags + ["--force-cpu"],
                                  to, env=env)
    else:
        err = "no budget left for a CPU fallback"
    if payload is not None:
        payload["backend_note"] = "cpu-fallback"
        payload["diag"] = diag
        print(json.dumps(payload))
        return
    diag.append(err)

    # total failure: still honor the one-JSON-line contract, rc=0.  The
    # backend_note tag lets artifact-promotion scripts refuse to overwrite
    # a previously captured real measurement with this sentinel.
    print(json.dumps({
        "metric": "AC SA-PINN training throughput (full minimax step)",
        "value": 0, "unit": "collocation-pts/sec/chip",
        "vs_baseline": None, "backend_note": "total-failure", "diag": diag,
    }))


if __name__ == "__main__":
    main()
