"""Burgers data assimilation (reference ``examples/burgers-assimilate.py``).

Forward Burgers solve with an extra data-fit loss over NS=200 sparse
observations of the solution at t=0.76.  The reference script targets the
removed ``CollocationSolver1D`` and its ND solver stores but never *uses*
the assimilation data (SURVEY §3.6); here ``compile_data`` adds a real
``Data`` loss term.
"""

import numpy as np

from _common import example_args, scaled

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC, dirichletBC,
                              grad)
from tensordiffeq_tpu.exact import burgers_solution


def main():
    args = example_args("Burgers with data assimilation")

    x, t, usol = burgers_solution()

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(scaled(args, 10_000, 1_000), seed=0)

    bcs = [IC(domain, [lambda x: -np.sin(np.pi * x)], var=[["x"]], n_values=60),
           dirichletBC(domain, val=0.0, var="x", target="upper"),
           dirichletBC(domain, val=0.0, var="x", target="lower")]

    def f_model(u, x, t):
        u_x, u_t = grad(u, "x"), grad(u, "t")
        u_xx = grad(u_x, "x")
        return u_t(x, t) + u(x, t) * u_x(x, t) - (0.05 / np.pi) * u_xx(x, t)

    # sparse observations: NS points at the single time slice t[75]
    NS = 200 if not args.quick else 40
    rng = np.random.RandomState(0)
    idx_xs = rng.choice(x.shape[0], NS, replace=False)
    it = 75
    x_s = x[idx_xs].reshape(-1, 1)
    t_s = np.full_like(x_s, t[it])
    y_s = usol[idx_xs, it].reshape(-1, 1)

    widths = [128] * 4 if not args.quick else [32] * 2
    solver = CollocationSolverND(assimilate=True)
    solver.compile([2, *widths, 1], f_model, domain, bcs)
    solver.compile_data(x_s, t_s, y_s)
    solver.fit(tf_iter=scaled(args, 10_000, 200),
               newton_iter=scaled(args, 1_000, 50))

    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    # NOTE: nu here is 0.05/pi (the reference's assimilation variant) while
    # the fixture solves nu=0.01/pi, so L2 is indicative only — the check
    # that matters is that the Data loss is active and decreasing
    err = tdq.find_L2_error(u_pred, usol.reshape(-1, 1))
    data_losses = [rec["Data"] for rec in solver.losses if "Data" in rec]
    print(f"Error u (vs nu=0.01/pi fixture): {err:e}; "
          f"Data loss {data_losses[0]:.3e} -> {data_losses[-1]:.3e}")
    return solver


if __name__ == "__main__":
    main()
