"""Shared CLI plumbing for the example scripts.

Every example accepts ``--quick`` (tiny iteration counts + small nets, for
smoke tests/CI) and ``--plot PATH`` (save figures instead of interactive
windows).  Full-size defaults reproduce the reference configs recorded in
``BASELINE.md``.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def example_args(description: str, flags=(), **extra):
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--quick", action="store_true",
                    help="tiny config for smoke testing")
    ap.add_argument("--plot", default=None, metavar="PATH",
                    help="save diagnostic plots under this directory")
    for flag in flags:
        ap.add_argument(f"--{flag}", action="store_true")
    for name, (default, help_) in extra.items():
        ap.add_argument(f"--{name}", type=type(default), default=default,
                        help=help_)
    return ap.parse_args()


def scaled(args, full: int, quick: int) -> int:
    return quick if args.quick else full
