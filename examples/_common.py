"""Shared CLI plumbing for the example scripts.

Every example accepts ``--quick`` (tiny iteration counts + small nets, for
smoke tests/CI) and ``--plot PATH`` (save figures instead of interactive
windows).  Full-size defaults reproduce the reference configs recorded in
``BASELINE.md``.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_PROBE_CACHE = os.path.join(tempfile.gettempdir(), "tdq_backend_probe.json")
_PROBE_TTL = 600  # seconds


def resolve_backend(timeout: int = 120) -> str:
    """Pin a usable JAX platform *before* first backend use.

    Honours ``TDQ_PLATFORM`` (e.g. ``TDQ_PLATFORM=cpu``); otherwise probes
    the default backend in a subprocess with a timeout and pins CPU when it
    is unreachable — on this class of host a TPU tunnel can hang backend
    init indefinitely, which would otherwise hang every example.  The probe
    outcome is cached for 10 minutes."""
    import jax

    want = os.environ.get("TDQ_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)
        return want
    already = getattr(jax.config, "jax_platforms", None)
    if already:  # something (conftest, caller) pinned a platform — keep it
        return already

    backend = None
    try:
        with open(_PROBE_CACHE) as fh:
            cached = json.load(fh)
        if time.time() - cached["ts"] < _PROBE_TTL:
            backend = cached["backend"]
    except Exception:
        pass
    if backend is None:
        import subprocess
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout)
            out = (probe.stdout or "").strip().splitlines()
            backend = out[-1] if probe.returncode == 0 and out else "cpu"
        except Exception:
            backend = "cpu"
        try:
            with open(_PROBE_CACHE, "w") as fh:
                json.dump({"ts": time.time(), "backend": backend}, fh)
        except OSError:
            pass
    if backend == "cpu":
        print("[tdq] default backend unreachable; pinning CPU",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
    return backend


def example_args(description: str, flags=(), **extra):
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--quick", action="store_true",
                    help="tiny config for smoke testing")
    ap.add_argument("--plot", default=None, metavar="PATH",
                    help="save diagnostic plots under this directory")
    for flag in flags:
        ap.add_argument(f"--{flag}", action="store_true")
    for name, (default, help_) in extra.items():
        ap.add_argument(f"--{name}", type=type(default), default=default,
                        help=help_)
    args = ap.parse_args()
    resolve_backend()
    return args


def scaled(args, full: int, quick: int) -> int:
    return quick if args.quick else full


def zoo_spec(entry, quick: bool, quick_budget=(200, 100), **overrides):
    """Resolve an example's operating point from its zoo registry entry
    (``tensordiffeq_tpu/zoo`` — the single source of truth; entry↔example
    drift is structurally impossible): the declared ``full`` size, or for
    ``--quick`` the declared ``micro`` problem at smoke iteration counts
    (the CI wall cannot afford micro's real budget).  Non-zero
    ``overrides`` (``n_f=``, ``widths=``, ``budget=``) are CLI scale
    knobs layered on top."""
    import dataclasses

    from tensordiffeq_tpu.zoo import Budget

    spec = entry.spec("micro" if quick else "full")
    if quick:
        spec = dataclasses.replace(spec, budget=Budget(*quick_budget))
    clean = {k: v for k, v in overrides.items() if v}
    if clean:
        spec = dataclasses.replace(spec, **clean)
    return spec


def fit_resumable(solver, tf_iter: int, newton_iter: int = 0,
                  quick: bool = False, **fit_kw):
    """``solver.fit`` with optional cross-run resume.

    When ``TDQ_CKPT`` names a directory, training state checkpoints there
    at chunk boundaries (``fit(checkpoint_dir=)``) and a rerun of the same
    example picks up where a killed run stopped — the watcher's full-size
    TPU runs live behind an intermittent tunnel, and an 85-minute config
    that dies at minute 80 must not restart from zero on the next window.
    Both phases are credited on resume: Adam epochs ride in the restored
    loss history, completed L-BFGS iterations in the checkpoint's
    ``newton_done``.  A COMPLETED run removes the checkpoint, so a later
    deliberate re-measurement trains from scratch instead of silently
    resuming a finished run.  Without ``TDQ_CKPT`` (or with
    ``quick=True`` — pass ``args.quick``; a smoke run must never seed a
    full run's resume point) this is exactly ``solver.fit``."""
    import shutil

    ck = None if quick else os.environ.get("TDQ_CKPT")
    if not ck:
        return solver.fit(tf_iter=tf_iter, newton_iter=newton_iter, **fit_kw)
    done = n_done = 0
    if os.path.exists(os.path.join(ck, "tdq_meta.json")) \
            or os.path.exists(os.path.join(ck + ".old", "tdq_meta.json")):
        try:
            solver.restore_checkpoint(ck)
            done = min(len(solver.losses), tf_iter)
            n_done = min(getattr(solver, "newton_done", 0), newton_iter)
            print(f"[tdq] resumed from {ck}: {done} Adam epochs, "
                  f"{n_done} L-BFGS iters", flush=True)
        except Exception as e:
            print(f"[tdq] checkpoint in {ck} not restorable "
                  f"({type(e).__name__}: {e}); starting fresh", flush=True)
    out = solver.fit(tf_iter=tf_iter - done,
                     newton_iter=newton_iter - n_done,
                     checkpoint_dir=ck,
                     checkpoint_every=max(200, tf_iter // 10), **fit_kw)
    for d in (ck, ck + ".old", ck + ".tmp"):
        shutil.rmtree(d, ignore_errors=True)
    return out
