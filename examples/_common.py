"""Shared CLI plumbing for the example scripts.

Every example accepts ``--quick`` (tiny iteration counts + small nets, for
smoke tests/CI) and ``--plot PATH`` (save figures instead of interactive
windows).  Full-size defaults reproduce the reference configs recorded in
``BASELINE.md``.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_PROBE_CACHE = os.path.join(tempfile.gettempdir(), "tdq_backend_probe.json")
_PROBE_TTL = 600  # seconds


def resolve_backend(timeout: int = 120) -> str:
    """Pin a usable JAX platform *before* first backend use.

    Honours ``TDQ_PLATFORM`` (e.g. ``TDQ_PLATFORM=cpu``); otherwise probes
    the default backend in a subprocess with a timeout and pins CPU when it
    is unreachable — on this class of host a TPU tunnel can hang backend
    init indefinitely, which would otherwise hang every example.  The probe
    outcome is cached for 10 minutes."""
    import jax

    want = os.environ.get("TDQ_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)
        return want
    already = getattr(jax.config, "jax_platforms", None)
    if already:  # something (conftest, caller) pinned a platform — keep it
        return already

    backend = None
    try:
        with open(_PROBE_CACHE) as fh:
            cached = json.load(fh)
        if time.time() - cached["ts"] < _PROBE_TTL:
            backend = cached["backend"]
    except Exception:
        pass
    if backend is None:
        import subprocess
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout)
            out = (probe.stdout or "").strip().splitlines()
            backend = out[-1] if probe.returncode == 0 and out else "cpu"
        except Exception:
            backend = "cpu"
        try:
            with open(_PROBE_CACHE, "w") as fh:
                json.dump({"ts": time.time(), "backend": backend}, fh)
        except OSError:
            pass
    if backend == "cpu":
        print("[tdq] default backend unreachable; pinning CPU",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
    return backend


def example_args(description: str, flags=(), **extra):
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--quick", action="store_true",
                    help="tiny config for smoke testing")
    ap.add_argument("--plot", default=None, metavar="PATH",
                    help="save diagnostic plots under this directory")
    for flag in flags:
        ap.add_argument(f"--{flag}", action="store_true")
    for name, (default, help_) in extra.items():
        ap.add_argument(f"--{name}", type=type(default), default=default,
                        help=help_)
    args = ap.parse_args()
    resolve_backend()
    return args


def scaled(args, full: int, quick: int) -> int:
    return quick if args.quick else full
