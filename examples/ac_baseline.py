"""Allen-Cahn baseline forward PINN (reference ``examples/AC-baseline.py``).

u_t - 0.0001 u_xx + 5u^3 - 5u = 0 on x in [-1,1], t in [0,1];
u(x,0) = x^2 cos(pi x), periodic in x (value + first derivative).
N_f=50k, 2-128x4-1 tanh MLP, 10k Adam + 10k L-BFGS.
"""

import numpy as np

from _common import example_args, scaled, fit_resumable

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC, grad,
                              periodicBC)
from tensordiffeq_tpu.exact import allen_cahn_solution


def build_problem(n_f: int, nx: int = 512, nt: int = 201, seed: int = 0):
    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], nx)
    domain.add("t", [0.0, 1.0], nt)
    domain.generate_collocation_points(n_f, seed=seed)

    def func_ic(x):
        return x ** 2 * np.cos(np.pi * x)

    def deriv_model(u, x, t):
        return u(x, t), grad(u, "x")(x, t)

    bcs = [IC(domain, [func_ic], var=[["x"]]),
           periodicBC(domain, ["x"], [deriv_model])]

    def f_model(u, x, t):
        u_xx = grad(grad(u, "x"), "x")
        u_t = grad(u, "t")
        uv = u(x, t)
        return u_t(x, t) - 0.0001 * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv

    return domain, bcs, f_model


def build_sa_solver(n_f: int, nx: int, nt: int, widths, periodic=False,
                    seed: int = 0, verbose: bool = False):
    """The flagship SA config as ONE shared builder (reference
    ``AC-SA.py:12,55-56,64``): λ_res ~ U[0,1] per collocation point,
    λ_IC ~ 100·U[0,1] per IC point, minimax via Adaptive_type=1;
    ``periodic=True`` swaps in the exactly-periodic harmonic ansatz
    (beyond-reference ``periodic_net``, generic residual engine).  Used
    by ``ac_sa.py``, the north-star drivers, and the CPU hedges so the
    arms can never de-synchronize.  ``seed`` drives ALL THREE RNG
    consumers — the collocation draw (``build_problem``), the network
    init (``CollocationSolverND(seed=)``), and the λ init — so one seed
    pins the whole run."""
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import CollocationSolverND

    domain, bcs, f_model = build_problem(n_f, nx=nx, nt=nt, seed=seed)
    rng = np.random.RandomState(seed)
    layers = [2, *widths, 1]
    network = tdq.periodic_net(layers, domain, ["x"]) if periodic else None
    solver = CollocationSolverND(verbose=verbose, seed=seed)
    solver.compile(
        layers, f_model, domain, bcs, Adaptive_type=1,
        dict_adaptive={"residual": [True], "BCs": [True, False]},
        init_weights={"residual": [rng.rand(n_f, 1)],
                      "BCs": [100.0 * rng.rand(nx, 1), None]},
        network=network)
    return solver


def evaluate(solver, args, name):
    x, t, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = tdq.find_L2_error(u_pred, usol.reshape(-1, 1))
    print(f"Error u: {err:e}")
    if args.plot:
        tdq.plotting.plot_solution_domain1D(
            solver, [x, t], ub=[1.0, 1.0], lb=[-1.0, 0.0], Exact_u=usol,
            save_path=f"{args.plot}/{name}.png", best_model=True)
    return err


def main():
    args = example_args(
        "Allen-Cahn baseline forward PINN",
        telemetry=("", "write a JSONL telemetry run log under this "
                       "directory and print telemetry.report() at the end"))
    n_f = scaled(args, 50_000, 2_000)
    domain, bcs, f_model = build_problem(n_f, nx=512 if not args.quick else 64,
                                         nt=201 if not args.quick else 21)
    widths = [128] * 4 if not args.quick else [32] * 2
    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs)
    tf_iter = scaled(args, 10_000, 200)
    newton_iter = scaled(args, 10_000, 100)
    if args.telemetry:
        # subscribe instead of scraping stdout: the run's config, per-epoch
        # losses/grad-norm, step-time split, and any divergence land in
        # <dir>/events.jsonl, and the report renders the diagnosis
        with tdq.telemetry.RunLogger(
                args.telemetry,
                config={"example": "ac_baseline", "n_f": n_f,
                        "tf_iter": tf_iter, "newton_iter": newton_iter,
                        "widths": widths}) as run:
            fit_resumable(solver, quick=args.quick, tf_iter=tf_iter,
                          newton_iter=newton_iter, telemetry=run)
        print(tdq.telemetry.report(args.telemetry))
    else:
        fit_resumable(solver, quick=args.quick, tf_iter=tf_iter,
                      newton_iter=newton_iter)
    return evaluate(solver, args, "ac_baseline")


if __name__ == "__main__":
    main()
