"""Allen-Cahn baseline forward PINN (reference ``examples/AC-baseline.py``).

u_t - 0.0001 u_xx + 5u^3 - 5u = 0 on x in [-1,1], t in [0,1];
u(x,0) = x^2 cos(pi x), periodic in x (value + first derivative).
N_f=50k, 2-128x4-1 tanh MLP, 10k Adam + 10k L-BFGS.
"""

import numpy as np

from _common import example_args, scaled, fit_resumable

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import CollocationSolverND
from tensordiffeq_tpu.exact import allen_cahn_solution


def _sa_spec(n_f: int, nx: int, nt: int, widths):
    """An explicit operating point over the zoo entry's declared ``full``
    size (the registry owns the problem; callers own the scale knobs)."""
    import dataclasses

    from tensordiffeq_tpu import zoo

    return dataclasses.replace(zoo.get("allen-cahn-sa").spec("full"),
                               n_f=n_f, widths=tuple(widths),
                               grid=(nx, nt))


def build_problem(n_f: int, nx: int = 512, nt: int = 201, seed: int = 0):
    """The Allen-Cahn problem, resolved from the zoo registry (entry
    ``allen-cahn-sa`` — single source of truth); the SA compile config is
    dropped, this is the plain baseline."""
    from tensordiffeq_tpu import zoo

    entry = zoo.get("allen-cahn-sa")
    problem = entry.build(_sa_spec(n_f, nx, nt, (32,)), seed=seed)
    return problem.domain, list(problem.bcs), problem.f_model


def build_sa_solver(n_f: int, nx: int, nt: int, widths, periodic=False,
                    seed: int = 0, verbose: bool = False):
    """The flagship SA config as ONE shared builder (reference
    ``AC-SA.py:12,55-56,64``): λ_res ~ U[0,1] per collocation point,
    λ_IC ~ 100·U[0,1] per IC point, minimax via Adaptive_type=1 — now
    resolved from the zoo registry (entry ``allen-cahn-sa``), so this
    wrapper, the scorecard, and the north-star drivers share ONE
    declaration and can never de-synchronize.  ``periodic=True`` swaps in
    the exactly-periodic harmonic ansatz (beyond-reference
    ``periodic_net``, generic residual engine).  ``seed`` drives ALL
    THREE RNG consumers — the collocation draw, the network init, and
    the λ init — so one seed pins the whole run."""
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import zoo

    network_factory = None
    if periodic:
        def network_factory(layers, domain):
            return tdq.periodic_net(layers, domain, ["x"])
    return zoo.build_solver(zoo.get("allen-cahn-sa"),
                            spec=_sa_spec(n_f, nx, nt, widths), seed=seed,
                            network_factory=network_factory,
                            verbose=verbose)


def evaluate(solver, args, name):
    x, t, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = tdq.find_L2_error(u_pred, usol.reshape(-1, 1))
    print(f"Error u: {err:e}")
    if args.plot:
        tdq.plotting.plot_solution_domain1D(
            solver, [x, t], ub=[1.0, 1.0], lb=[-1.0, 0.0], Exact_u=usol,
            save_path=f"{args.plot}/{name}.png", best_model=True)
    return err


def main():
    args = example_args(
        "Allen-Cahn baseline forward PINN",
        telemetry=("", "write a JSONL telemetry run log under this "
                       "directory and print telemetry.report() at the end"))
    n_f = scaled(args, 50_000, 2_000)
    domain, bcs, f_model = build_problem(n_f, nx=512 if not args.quick else 64,
                                         nt=201 if not args.quick else 21)
    widths = [128] * 4 if not args.quick else [32] * 2
    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs)
    tf_iter = scaled(args, 10_000, 200)
    newton_iter = scaled(args, 10_000, 100)
    if args.telemetry:
        # subscribe instead of scraping stdout: the run's config, per-epoch
        # losses/grad-norm, step-time split, and any divergence land in
        # <dir>/events.jsonl, and the report renders the diagnosis
        with tdq.telemetry.RunLogger(
                args.telemetry,
                config={"example": "ac_baseline", "n_f": n_f,
                        "tf_iter": tf_iter, "newton_iter": newton_iter,
                        "widths": widths}) as run:
            fit_resumable(solver, quick=args.quick, tf_iter=tf_iter,
                          newton_iter=newton_iter, telemetry=run)
        print(tdq.telemetry.report(args.telemetry))
    else:
        fit_resumable(solver, quick=args.quick, tf_iter=tf_iter,
                      newton_iter=newton_iter)
    return evaluate(solver, args, "ac_baseline")


if __name__ == "__main__":
    main()
