"""Nonlinear Schrödinger — the classical 2-output PINN benchmark
(Raissi et al. 2019 §3.1.1).

``i h_t + 0.5 h_xx + |h|^2 h = 0`` on x in [-5, 5], t in [0, pi/2], with
``h(x, 0) = 2 sech(x)`` and periodic BCs (value + first derivative) in x.
The network has TWO outputs — h = u + iv — exercising the coupled-system
surface the reference supports (tuple residuals + per-output ICs,
``models.py:189-191``) but ships no example of.  Truth: the split-step
Fourier spectral solution in ``tensordiffeq_tpu.exact``.

Since PR 16 the tuple-returning ``f_model`` adopts the fused minimax
engine as a TWO-equation system (watch for ``[fuse] minimax engine
adopted`` at compile): both residuals, their per-equation λ channels,
and every cotangent reduce in one fusion (``ops/pallas_minimax``), so
the coupled benchmark trains on the same fast path as the scalar
examples — the measured step-time reduction is in ``bench.py --mode
minimax`` (``system`` block) and a convergence row in CONVERGENCE.md.
"""

import numpy as np

from _common import example_args, scaled, fit_resumable

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC, grad,
                              periodicBC)
from tensordiffeq_tpu.exact import schrodinger_solution


def build_problem(n_f: int, nx: int = 256, nt: int = 201, seed: int = 0):
    t_final = float(np.pi / 2)
    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-5.0, 5.0], nx)
    domain.add("t", [0.0, t_final], nt)
    domain.generate_collocation_points(n_f, seed=seed)

    # h(x, 0) = 2 sech(x):  u = 2 sech(x), v = 0
    ics = IC(domain,
             [lambda x: 2.0 / np.cosh(x), lambda x: 0.0 * x],
             var=[["x"], ["x"]])

    def deriv_model(u, x, t):
        return (u[0](x, t), u[1](x, t),
                grad(u[0], "x")(x, t), grad(u[1], "x")(x, t))

    per = periodicBC(domain, ["x"], [deriv_model])

    def f_model(u, x, t):
        uv, vv = u[0](x, t), u[1](x, t)
        sq = uv ** 2 + vv ** 2
        f_u = grad(u[0], "t")(x, t) + 0.5 * grad(grad(u[1], "x"), "x")(x, t) \
            + sq * vv
        f_v = grad(u[1], "t")(x, t) - 0.5 * grad(grad(u[0], "x"), "x")(x, t) \
            - sq * uv
        return f_u, f_v

    return domain, [ics, per], f_model


def evaluate(solver, args, name):
    x, t, h = schrodinger_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    pred, _ = solver.predict(Xg, best_model=True)
    h_pred = np.sqrt(pred[:, 0] ** 2 + pred[:, 1] ** 2)
    h_true = np.abs(h).reshape(-1)
    err = tdq.find_L2_error(h_pred, h_true)
    print(f"Error u: {err:e}  (rel-L2 of |h|)")
    if args.plot:
        tdq.plotting.plot_solution_domain1D(
            solver, [x, t], ub=[5.0, float(np.pi / 2)], lb=[-5.0, 0.0],
            Exact_u=np.abs(h), save_path=f"{args.plot}/{name}.png",
            component="abs", best_model=True)
    return err


def main():
    args = example_args(
        "Nonlinear Schrödinger 2-output PINN",
        nf=(0, "override N_f (0 = config default)"),
        adam=(0, "override Adam iters (0 = config default)"),
        newton=(0, "override L-BFGS iters (0 = config default)"),
        width=(0, "override hidden width (0 = config default)"))
    n_f = args.nf or scaled(args, 20_000, 2_000)
    nx, nt = (256, 201) if not args.quick else (64, 21)
    domain, bcs, f_model = build_problem(n_f, nx=nx, nt=nt)
    w = args.width or (100 if not args.quick else 32)
    widths = [w] * (4 if not args.quick else 2)

    solver = CollocationSolverND()
    solver.compile([2, *widths, 2], f_model, domain, bcs)
    fit_resumable(solver, quick=args.quick, tf_iter=args.adam or scaled(args, 10_000, 200),
               newton_iter=args.newton or scaled(args, 10_000, 100))
    return evaluate(solver, args, "schrodinger")


if __name__ == "__main__":
    main()
