"""Nonlinear Schrödinger — the classical 2-output PINN benchmark
(Raissi et al. 2019 §3.1.1).

``i h_t + 0.5 h_xx + |h|^2 h = 0`` on x in [-5, 5], t in [0, pi/2], with
``h(x, 0) = 2 sech(x)`` and periodic BCs (value + first derivative) in x.
The network has TWO outputs — h = u + iv — and the tuple-returning
``f_model`` adopts the fused minimax engine as a TWO-equation system
(PR 16; watch for ``[fuse] minimax engine adopted`` at compile): both
residuals, their per-equation λ channels, and every cotangent reduce in
one fusion, so the coupled benchmark trains on the same fast path as the
scalar examples.

The problem declaration lives in the zoo registry
(``tensordiffeq_tpu.zoo``, entry ``schrodinger``) — this script resolves
its config from there; truth is the split-step Fourier spectral solution
in ``tensordiffeq_tpu.exact``.
"""

import dataclasses

import numpy as np

from _common import example_args, fit_resumable, zoo_spec

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import zoo
from tensordiffeq_tpu.exact import schrodinger_solution

ENTRY = zoo.get("schrodinger")


def evaluate(solver, args, name):
    x, t, h = schrodinger_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    pred, _ = solver.predict(Xg, best_model=True)
    h_pred = np.sqrt(pred[:, 0] ** 2 + pred[:, 1] ** 2)
    h_true = np.abs(h).reshape(-1)
    err = tdq.find_L2_error(h_pred, h_true)
    print(f"Error u: {err:e}  (rel-L2 of |h|)")
    if args.plot:
        tdq.plotting.plot_solution_domain1D(
            solver, [x, t], ub=[5.0, float(np.pi / 2)], lb=[-5.0, 0.0],
            Exact_u=np.abs(h), save_path=f"{args.plot}/{name}.png",
            component="abs", best_model=True)
    return err


def main():
    args = example_args(
        "Nonlinear Schrödinger 2-output PINN",
        nf=(0, "override N_f (0 = zoo-entry default)"),
        adam=(0, "override Adam iters (0 = zoo-entry default)"),
        newton=(0, "override L-BFGS iters (0 = zoo-entry default)"),
        width=(0, "override hidden width (0 = zoo-entry default)"))
    spec = zoo_spec(ENTRY, args.quick, n_f=args.nf)
    if args.width:
        spec = dataclasses.replace(
            spec, widths=(args.width,) * len(spec.widths))
    if args.adam or args.newton:
        spec = dataclasses.replace(
            spec, budget=zoo.Budget(args.adam or spec.budget.adam,
                                    args.newton or spec.budget.lbfgs))

    solver = zoo.build_solver(ENTRY, spec=spec)
    fit_resumable(solver, quick=args.quick, tf_iter=spec.budget.adam,
                  newton_iter=spec.budget.lbfgs)
    return evaluate(solver, args, "schrodinger")


if __name__ == "__main__":
    main()
