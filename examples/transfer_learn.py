"""Transfer learning / staged-LR resume (reference
``examples/transfer-learn.py``).

Train Allen-Cahn SA for a first leg, save, then resume twice with lowered
learning rates.  The reference can only checkpoint the Keras network (λ and
optimizer state are lost on reload, SURVEY §5); here the full training
state — params, λ, and Adam moments — round-trips through
``tensordiffeq_tpu.checkpoint``.
"""

import os
import tempfile

import numpy as np

from _common import example_args, scaled

from ac_baseline import build_problem, evaluate

from tensordiffeq_tpu import CollocationSolverND


def make_solver(args, n_f, nx, lr):
    domain, bcs, f_model = build_problem(n_f, nx=nx,
                                         nt=201 if not args.quick else 21)
    widths = [128] * 4 if not args.quick else [32] * 2
    rng = np.random.RandomState(0)
    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs, Adaptive_type=1,
                   dict_adaptive={"residual": [True], "BCs": [True, False]},
                   init_weights={"residual": [rng.rand(n_f, 1)],
                                 "BCs": [100.0 * rng.rand(nx, 1), None]},
                   lr=lr, lr_weights=lr)
    return solver


def main():
    args = example_args("Transfer learning with staged learning rates")
    n_f = scaled(args, 50_000, 2_000)
    nx = 512 if not args.quick else 64
    leg = scaled(args, 5_000, 100)

    ckpt_dir = os.path.join(tempfile.mkdtemp(), "ac_ckpt")

    solver = make_solver(args, n_f, nx, lr=0.005)
    solver.fit(tf_iter=leg)
    solver.save_checkpoint(ckpt_dir)
    print(f"leg 1 done, loss {solver.losses[-1]['Total Loss']:.4e}")

    # resume with 10x lower LR: fresh solver object, restore full state
    solver = make_solver(args, n_f, nx, lr=0.0005)
    solver.restore_checkpoint(ckpt_dir)
    solver.fit(tf_iter=leg)
    solver.save_checkpoint(ckpt_dir)
    print(f"leg 2 done, loss {solver.losses[-1]['Total Loss']:.4e}")

    solver = make_solver(args, n_f, nx, lr=0.00005)
    solver.restore_checkpoint(ckpt_dir)
    solver.fit(tf_iter=leg)
    print(f"leg 3 done, loss {solver.losses[-1]['Total Loss']:.4e}")

    return evaluate(solver, args, "transfer_learn")


if __name__ == "__main__":
    main()
