"""Allen-Cahn under chaos: the end-to-end resilience demo.

ONE supervised training run survives an injected NaN divergence (rollback
-> remedy ladder -> retry) AND an injected preemption (final checkpoint
flush -> in-process resume), completes its full budget, and leaves a run
log whose report narrates every failure and every heal.  Then a serving
leg under an injected fault rate shows transient op failures healing
invisibly behind retry, the circuit breaker opening and closing around a
sustained outage, and ZERO hung waiters.

Quick smoke (CPU, ~a minute)::

    python examples/ac_resilient.py --quick

Full config trains the flagship SA problem with the same chaos plan.
"""

import os
import shutil

import numpy as np

from _common import example_args, scaled

from tensordiffeq_tpu import telemetry
from tensordiffeq_tpu.resilience import (Chaos, ChaosFault, CircuitBreaker,
                                         CircuitOpenError, ResilientFit,
                                         RetryPolicy)
from tensordiffeq_tpu.serving import RequestBatcher
from tensordiffeq_tpu.telemetry import RunLogger, read_events


def main():
    args = example_args("Allen-Cahn resilience demo: chaos-injected "
                        "divergence + preemption + serving faults, all "
                        "recovered")
    from ac_baseline import build_sa_solver

    n_f = scaled(args, 10_000, 512)
    nx, nt = (64, 16) if args.quick else (512, 201)
    widths = [16, 16] if args.quick else [64, 64, 64]
    tf_iter = scaled(args, 2_000, 40)
    chunk = scaled(args, 100, 10)
    ck_every = chunk
    nan_at = scaled(args, 500, 15)        # divergence mid-run
    preempt_at = scaled(args, 1_500, 25)  # preemption later in the run

    run_dir = "runs/ac_resilient"
    ck = "runs/ac_resilient_ckpt"
    for d in (run_dir, ck, ck + ".old", ck + ".tmp"):
        shutil.rmtree(d, ignore_errors=True)

    solver = build_sa_solver(n_f, nx, nt, widths, verbose=not args.quick)

    # ---- training leg: NaN at epoch N + preemption, one supervised run --
    with RunLogger(run_dir, config={"n_f": n_f, "tf_iter": tf_iter,
                                    "nan_at": nan_at,
                                    "preempt_at": preempt_at}) as logger:
        with Chaos(nan_epoch=nan_at, preempt_epoch=preempt_at,
                   seed=0) as chaos:
            rf = ResilientFit(solver, ck, checkpoint_every=ck_every,
                              max_retries=3, telemetry=logger,
                              resume_on_preemption=True)
            rf.fit(tf_iter=tf_iter, newton_iter=0, chunk=chunk)
        print(f"\n[resilient] chaos fired: {chaos.fired}")
        print(f"[resilient] recoveries: {rf.recoveries}, "
              f"preemptions resumed: {rf.preemptions_resumed}")
        print(f"[resilient] trained {len(solver.losses)}/{tf_iter} epochs, "
              f"final loss {solver.losses[-1]['Total Loss']:.3e}")

        # ---- serving leg: fault rate healed by retry + breaker ----------
        engine = solver.export_surrogate().engine(
            min_bucket=64, max_bucket=256 if args.quick else 1024)
        batcher = RequestBatcher(
            engine, max_batch=256, max_latency_s=0.005,
            retry=RetryPolicy(max_attempts=4, base_delay_s=1e-3,
                              max_delay_s=1e-2, seed=0),
            breaker=CircuitBreaker(failure_threshold=8, reset_timeout_s=0.05),
            request_timeout_s=5.0)
        rng = np.random.RandomState(0)
        n_req = scaled(args, 400, 60)
        with Chaos(serving_fail_rate=0.25, seed=1) as serving_chaos:
            for _ in range(n_req):
                n = int(rng.randint(1, 17))
                X = np.stack([rng.uniform(-1, 1, n),
                              rng.uniform(0, 1, n)], -1).astype(np.float32)
                try:
                    batcher.submit(X)
                    batcher.poll()
                except (ChaosFault, CircuitOpenError):
                    pass  # injected fault past retries: counted in stats
            try:
                batcher.flush()
            except (ChaosFault, CircuitOpenError):
                pass
        stats = batcher.stats()
        print(f"[resilient] serving: {stats['requests']} served, "
              f"{stats['retried_ok']} batches healed by retry, "
              f"{stats['failed']} failed, {stats['timed_out']} timed out, "
              f"{stats['rejected']} fast-failed by the breaker "
              f"({serving_chaos.fired['serving']} faults injected)")
        assert stats["timed_out"] == 0, "no waiter may hang"

    # ---- the narrated trail --------------------------------------------
    print()
    print(telemetry.report(run_dir))
    kinds = {e["kind"] for e in read_events(run_dir)}
    need = {"divergence", "rollback", "remedy", "checkpoint", "preempt",
            "resume"}
    missing = need - kinds
    assert not missing, f"run log missing {missing}"
    print(f"\n[resilient] run log at {run_dir}/events.jsonl holds the full "
          "trail: " + ", ".join(sorted(need)))
    if os.environ.get("TDQ_KEEP_RUNS") != "1":
        for d in (ck, ck + ".old", ck + ".tmp"):
            shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    main()
