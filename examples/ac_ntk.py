"""Allen-Cahn with NTK-balanced loss weighting (Adaptive_type=3).

The reference *declares* this mode (``models.py:39``: "Neural Tangent
Kernel based adaptive methods", arXiv:2007.14527) but ships it as dead
code; here it works: per-term weights lambda_i = sum_j tr(K_j) / tr(K_i)
are recomputed from the tangent kernel every training chunk, covering all
terms — including the periodic BC, which the SA path cannot weight.
"""

from _common import example_args, scaled, fit_resumable

from ac_baseline import build_problem, evaluate

from tensordiffeq_tpu import CollocationSolverND


def main():
    args = example_args("Allen-Cahn with NTK weighting")
    n_f = scaled(args, 50_000, 2_000)
    domain, bcs, f_model = build_problem(n_f, nx=512 if not args.quick else 64,
                                         nt=201 if not args.quick else 21)
    widths = [128] * 4 if not args.quick else [32] * 2

    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs, Adaptive_type=3)
    fit_resumable(solver, quick=args.quick, tf_iter=scaled(args, 10_000, 200),
               newton_iter=scaled(args, 10_000, 100))
    lam = {k: [float(v) for v in vs] for k, vs in solver.lambdas.items()}
    print(f"final NTK weights: {lam}")
    return evaluate(solver, args, "ac_ntk")


if __name__ == "__main__":
    main()
