"""Allen-Cahn closed loop: serve a surrogate family -> inject parameter
drift -> autonomous drift-triggered retrain -> zero-downtime hot-swap,
with one corrupted v2 member survived by bit-validated rollback.

ROADMAP item 4 end to end — the train -> serve -> monitor -> retrain loop
running with no operator in it.  This script

1. trains a small Allen-Cahn coefficient family
   (:class:`~tensordiffeq_tpu.factory.SurrogateFactory`), exports the v1
   artifact batch and fleet-serves every member through a
   :class:`~tensordiffeq_tpu.fleet.FleetRouter`, with a
   :class:`~tensordiffeq_tpu.fleet.DriftMonitor` shadow-sampling the
   live ``u`` traffic through the engines' existing residual programs;
2. under a chaos scope, deterministically injects parameter drift into
   one tenant's SERVED params (``drift_inject`` — silent numeric rot on
   a live replica) and serves traffic until the monitor's
   ``residual_drift`` SLO objective trips;
3. lets the :class:`~tensordiffeq_tpu.fleet.RetrainController` run the
   whole cycle autonomously: factory retrain warm-started from the live
   members' (drifted) served params, v2 export, canary validation of
   every candidate against the pinned probe set, and an atomic
   per-tenant route flip with ZERO request-time compiles — while chaos
   tears one v2 member's artifact payload (``swap_corrupt_member``), so
   the swap must ship without that member: the checksum rejects the torn
   blob, the old engine keeps serving, and the rollback is proven
   bit-identical by probe replay;
4. prints the narrated telemetry report — the DRIFT / RETRAIN / CANARY /
   SWAPPED / ROLLED BACK trail an operator reads after the fact.
"""

import os
import tempfile

import numpy as np

from _common import example_args, scaled

from tensordiffeq_tpu import (IC, DomainND, SurrogateFactory, fleet, grad,
                              periodicBC, telemetry)
from tensordiffeq_tpu.resilience import Chaos

MIN_BUCKET, MAX_BUCKET = 64, 512


def f_model(u, x, t, th):
    u_xx = grad(grad(u, "x"), "x")
    u_t = grad(u, "t")
    uv = u(x, t)
    return u_t(x, t) - th * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv


def main():
    args = example_args(
        "Allen-Cahn closed loop: drift-triggered factory retrain + "
        "zero-downtime hot-swap, chaos-proven")
    quick = args.quick

    n_f = scaled(args, 10_000, 512)
    nx, nt = (256, 101) if not quick else (64, 16)
    layers = [2] + ([64] * 3 if not quick else [16] * 2) + [1]
    pre_iters = scaled(args, 600, 40)
    retrain_iters = scaled(args, 600, 40)
    chunk = scaled(args, 100, 20)
    thetas = [0.0008, 0.0010, 0.0012][: 2 if quick else 3]
    corrupt_member = 1

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], nx)
    domain.add("t", [0.0, 1.0], nt)
    domain.generate_collocation_points(n_f, seed=0)

    def func_ic(x):
        return x ** 2 * np.cos(np.pi * x)

    def deriv_model(u, x, t):
        return u(x, t), grad(u, "x")(x, t)

    def build_factory(init_params=None):
        bcs = [IC(domain, [func_ic], var=[["x"]]),
               periodicBC(domain, ["x"], [deriv_model])]
        return SurrogateFactory(layers, f_model, domain, bcs, thetas,
                                init_params=init_params, verbose=False)

    # -- v1: train the family, export, fleet-serve, monitor ------------- #
    work = tempfile.mkdtemp(prefix="tdq_closedloop_")
    run_dir = os.path.join(work, "run")
    factory = build_factory()
    factory.fit(tf_iter=pre_iters, chunk=chunk)
    v1 = os.path.join(work, "v1")
    factory.export_family(v1, min_bucket=MIN_BUCKET, max_bucket=MAX_BUCKET)
    print(f"[v1] {factory.n_members}-member family trained "
          f"({pre_iters} epochs) and exported -> {v1}")

    with telemetry.RunLogger(run_dir, config={"example": "ac_closedloop"}):
        router = fleet.FleetRouter(max_loaded=len(thetas) + 1)
        policy = fleet.TenantPolicy(min_bucket=MIN_BUCKET,
                                    max_bucket=MAX_BUCKET, max_batch=512,
                                    max_latency_s=0.005)
        members = router.register_family(
            v1, policy=policy, prefix="ac",
            f_models={m: factory.member_f_model(m)
                      for m in range(len(thetas))})
        monitor = fleet.DriftMonitor(router, sample_fraction=0.5,
                                     window=2, seed=0)
        rng = np.random.RandomState(0)

        def draw(n):
            return np.stack([rng.uniform(-1, 1, n),
                             rng.uniform(0, 1, n)], -1).astype(np.float32)

        probe = draw(MIN_BUCKET)
        for tenant in members.values():
            router.load(tenant)
            monitor.attach(tenant, probe)
        print(f"[serve] {len(members)} tenants live; monitoring "
              f"(sample 50%, threshold "
              f"{monitor.slo.max_residual_drift:g}x baseline)")

        reg = telemetry.default_registry()

        def compiles():
            return sum(v for k, v in reg.as_dict()["counters"].items()
                       if k.startswith("serving.engine.compiles"))

        # pre-drift snapshot of the member that will be corrupted in v2:
        # its OLD engine must keep serving bit-identically throughout
        victim = members[corrupt_member]
        u_victim_before = router.query(victim, probe)

        # -- the chaotic cycle: drift + a torn v2 member ---------------- #
        chaos = Chaos(drift_inject=0.6, swap_corrupt_member=corrupt_member,
                      seed=0)
        with chaos:
            served = 0
            while not monitor.tripped() and served < 200:
                tenant = list(members.values())[served % len(members)]
                monitor.query(tenant, draw(int(rng.randint(1, 17))))
                served += 1
            assert monitor.tripped(), "drift was injected but never tripped"
            print(f"[drift] injected into {list(monitor.tripped())}; "
                  f"tripped after {served} live queries at "
                  f"{max(monitor.drift(t) or 0 for t in members.values()):.1f}x "
                  "baseline")

            controller = fleet.RetrainController(
                router, monitor, build_factory, members,
                retrain_iters=retrain_iters, chunk=chunk,
                resample_every=0 if quick else chunk, gate_ratio=5.0,
                export_kw=dict(min_bucket=MIN_BUCKET,
                               max_bucket=MAX_BUCKET),
                workdir=work, verbose=False)
            pre = compiles()
            cycle = controller.run_cycle()
        assert chaos.fired["drift_inject"] == 1
        assert chaos.fired["swap_corrupt"] == 1, \
            "the v2 member artifact was never torn"

        # -- verdicts: swap shipped WITHOUT the corrupted member -------- #
        swapped = {v["tenant"] for v in cycle["swapped"]}
        rolled = {v["tenant"]: v for v in cycle["rolled_back"]}
        assert victim in rolled, "the torn member was not rejected"
        assert rolled[victim]["reason"] == "artifact_rejected", rolled
        assert rolled[victim]["bit_identical"], \
            "old engine's probe replay changed across the rollback"
        assert swapped, "no healthy member was swapped"
        # the drift lands on the FIRST tenant probed (ac000), never the
        # victim — so the victim's old engine must answer bit-identically
        # across the whole cycle, torn v2 artifact and all
        u_victim_after = router.query(victim, probe)
        assert np.array_equal(u_victim_before, u_victim_after), \
            "the rolled-back tenant's answers changed"
        for tenant in members.values():
            router.query(tenant, draw(16))
        assert compiles() - pre == 0, \
            "the retrain/swap cycle compiled at request time"
        print(f"[swap] {len(swapped)} tenant(s) cut over "
              f"(generations={cycle['generations']}, retrain "
              f"{cycle['retrain_wall_s']:.1f}s); {victim} rolled back to "
              "its old engine (torn artifact -> checksum rejection, "
              "bit-identical replay); 0 request-time compiles")

    print(telemetry.report(run_dir))


if __name__ == "__main__":
    main()  # plain call: test_examples runs this in-process via runpy
