"""Allen-Cahn coefficient discovery — inverse problem
(reference ``examples/AC-discovery.py`` and ``examples/AC-inference.py``,
which is the same DiscoveryModel workflow under a misleading filename).

Learns c1 (diffusion) and c2 (reaction) in
``u_t - c1 u_xx + c2 u^3 - c2 u = 0`` from the full 512x201 solution grid,
optionally with SA collocation weights (``--no-sa`` for the plain variant).
True values: c1 = 0.0001, c2 = 5.0.

Round-2 promotion demo: the run trains on the fused Taylor residual engine
(auto-selected with numeric cross-check), checkpoints mid-run, and resumes
from the checkpoint — state (coefficients, SA weights, Adam moments)
round-trips exactly.
"""

import os
import tempfile

import numpy as np

from _common import example_args, scaled

from tensordiffeq_tpu import DiscoveryModel, grad
from tensordiffeq_tpu.exact import allen_cahn_solution


def main():
    args = example_args(
        "Allen-Cahn coefficient discovery", flags=("no-sa",),
        iters=(0, "override total Adam iters (0 = config default)"),
        lr_vars=("", "coefficient learning rate: one float or a "
                     "comma-separated per-coefficient list (empty = library "
                     "default). '2e-5,0.01' matches the c1/c2 scale split — "
                     "a single rate parks c1 at an Adam noise floor ~10x "
                     "its 1e-4 target (see DiscoveryModel.compile)"),
        out=("", "write a JSON summary + coefficient trajectory here"))
    use_sa = not args.no_sa

    x, t, usol = allen_cahn_solution()
    if args.quick:
        x, t, usol = x[::8], t[::8], usol[::8, ::8]
    X = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)

    def f_model(u, var, x, t):
        c1, c2 = var
        u_xx = grad(grad(u, "x"), "x")
        uv = u(x, t)
        return grad(u, "t")(x, t) - c1 * u_xx(x, t) + c2 * uv ** 3 - c2 * uv

    rng = np.random.RandomState(0)
    col_weights = rng.rand(X.shape[0], 1) if use_sa else None
    widths = [128] * 4 if not args.quick else [32] * 2

    lr_vars_kw = {}
    if args.lr_vars:
        vals = [float(s) for s in args.lr_vars.split(",")]
        if len(vals) > 1:
            lr_vars_kw = {"lr_vars": vals}
        elif vals[0] != 0.0:  # bare '0' keeps its old meaning: default
            lr_vars_kw = {"lr_vars": vals[0]}

    def build():
        model = DiscoveryModel()
        model.compile([2, *widths, 1], f_model,
                      [X[:, 0:1], X[:, 1:2]], u_star, var=[0.0, 0.0],
                      col_weights=col_weights, varnames=["x", "t"],
                      **lr_vars_kw)
        return model

    total = args.iters or scaled(args, 10_000, 300)
    leg = total // 2

    model = build()
    if model._fused_residual is not None:
        print("[discovery] fused Taylor residual engine active")
    model.fit(tf_iter=leg)

    # checkpoint mid-run and resume into a FRESH model (full-state restore)
    ckpt = os.path.join(tempfile.mkdtemp(), "ac_discovery_ckpt")
    model.save_checkpoint(ckpt)
    model = build()
    model.restore_checkpoint(ckpt)
    model.fit(tf_iter=total - leg)

    c1, c2 = (float(v) for v in model.vars)
    print(f"c1 = {c1:.6f} (true 0.0001), c2 = {c2:.4f} (true 5.0)")
    if args.out:
        import json
        summary = {"grid": f"{len(x)}x{len(t)}", "net": f"2-{widths[0]}x{len(widths)}-1",
                   "adam": total, "lr_vars": args.lr_vars or None, "sa": use_sa,
                   "c1": c1, "c1_true": 0.0001, "c1_abs_err": abs(c1 - 0.0001),
                   "c2": c2, "c2_true": 5.0, "c2_rel_err": abs(c2 - 5.0) / 5.0,
                   "final_loss": float(model.losses[-1]),
                   "trajectory_every10": model.var_history[::10]}
        with open(args.out, "w") as fh:
            json.dump(summary, fh)
    return model


if __name__ == "__main__":
    main()
