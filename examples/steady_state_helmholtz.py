"""Steady-state Helmholtz equation (reference ``examples/steady-state.py``).

u_xx + u_yy + k^2 u = forcing on [-1,1]^2 with homogeneous Dirichlet BCs,
forcing chosen so the exact solution is sin(pi x) sin(4 pi y).
No time variable — a pure boundary-value problem.
"""

import numpy as np

from _common import example_args, scaled

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import CollocationSolverND, DomainND, dirichletBC, grad


def main():
    args = example_args("Helmholtz steady state")
    a1, a2, ksq = 1.0, 4.0, 1.0

    domain = DomainND(["x", "y"])
    fid = 1001 if not args.quick else 64
    domain.add("x", [-1.0, 1.0], fid)
    domain.add("y", [-1.0, 1.0], fid)
    domain.generate_collocation_points(scaled(args, 10_000, 1_000), seed=0)

    bcs = [dirichletBC(domain, val=0.0, var=v, target=tg)
           for v in ("x", "y") for tg in ("upper", "lower")]

    def f_model(u, x, y):
        import jax.numpy as jnp
        u_xx = grad(grad(u, "x"), "x")(x, y)
        u_yy = grad(grad(u, "y"), "y")(x, y)
        pi = np.pi
        forcing = (-(a1 * pi) ** 2 * jnp.sin(a1 * pi * x) * jnp.sin(a2 * pi * y)
                   - (a2 * pi) ** 2 * jnp.sin(a1 * pi * x) * jnp.sin(a2 * pi * y)
                   + ksq * jnp.sin(a1 * pi * x) * jnp.sin(a2 * pi * y))
        return u_xx + u_yy + ksq * u(x, y) - forcing

    widths = [50] * 4 if not args.quick else [32] * 2
    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs)
    solver.fit(tf_iter=scaled(args, 10_000, 200),
               newton_iter=scaled(args, 10_000, 100))

    n = 201
    xv, yv = np.meshgrid(np.linspace(-1, 1, n), np.linspace(-1, 1, n))
    exact = np.sin(a1 * np.pi * xv) * np.sin(a2 * np.pi * yv)
    Xg = np.hstack([xv.reshape(-1, 1), yv.reshape(-1, 1)])
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = tdq.find_L2_error(u_pred, exact.reshape(-1, 1))
    print(f"Error u: {err:e}")
    return err


if __name__ == "__main__":
    main()
