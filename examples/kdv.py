"""Korteweg-de Vries single-soliton forward PINN (beyond-reference example:
exercises the fused engine's unmixed third-order derivative path).

u_t + 6 u u_x + u_xxx = 0 on x in [-10, 10], t in [0, 1], with the exact
travelling soliton u(x, t) = (c/2) sech^2(sqrt(c)/2 (x - c t - x0)):
the initial condition and Dirichlet boundaries are taken from it, and the
run validates relative L2 against it on a grid.
"""

import numpy as np

from _common import example_args, scaled, fit_resumable

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC, d,
                              FunctionDirichletBC, grad)

C = 4.0     # soliton speed
X0 = -5.0   # initial crest position


def soliton(x, t):
    s = np.sqrt(C) / 2.0 * (x - C * t - X0)
    return C / 2.0 / np.cosh(s) ** 2


def main():
    args = example_args("KdV single-soliton forward PINN (3rd-order fused)",
                        nf=(0, "override N_f (0 = config default)"),
                        adam=(0, "override Adam iters (0 = config default)"),
                        newton=(0, "override L-BFGS iters (0 = config "
                                   "default; Adam-only runs aren't "
                                   "expressible here)"))

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-10.0, 10.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(
        args.nf or scaled(args, 20_000, 1_500), seed=0)

    bcs = [IC(domain, [lambda x: soliton(x, 0.0)], var=[["x"]]),
           FunctionDirichletBC(domain, [lambda t: soliton(-10.0, t)],
                               var="x", target="lower",
                               func_inputs=[["t"]]),
           FunctionDirichletBC(domain, [lambda t: soliton(10.0, t)],
                               var="x", target="upper",
                               func_inputs=[["t"]])]

    def f_model(u, x, t):
        return (grad(u, "t")(x, t) + 6.0 * u(x, t) * grad(u, "x")(x, t)
                + d(u, "x", 3)(x, t))

    widths = [30] * 4 if not args.quick else [20] * 3
    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs)
    assert solver._fused_residual is not None, "3rd-order path should fuse"
    fit_resumable(solver, quick=args.quick, tf_iter=args.adam or scaled(args, 10_000, 200),
               newton_iter=args.newton or scaled(args, 10_000, 100))

    x = domain.linspace("x")
    t = domain.linspace("t")
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_star = soliton(Xg[:, 0:1], Xg[:, 1:2])
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = tdq.find_L2_error(u_pred, u_star)
    print(f"KdV soliton relative L2: {err:.3e}")
    return err


if __name__ == "__main__":
    main()
