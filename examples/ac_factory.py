"""Allen-Cahn surrogate FACTORY: train a coefficient sweep as ONE
vmapped program -> export the artifact batch -> fleet-serve the members.

ROADMAP item 3 end-to-end — the production workload where users ask for
*their* diffusion coefficient and the factory has already trained the
neighborhood:

1. trains a family of Allen-Cahn surrogates over a sweep of diffusion
   coefficients θ with :class:`~tensordiffeq_tpu.factory.
   SurrogateFactory` — per-member params, SA λ and Adam moments stacked
   along a model axis, the fused minimax step vmapped over it, one
   jitted train step for the whole family;
2. solo-trains TWO of the members as matched-seed references
   (``CollocationSolverND(seed = factory seed + m)`` with θ_m baked)
   and asserts each factory member tracks its reference within the
   documented family cross-check band (vmap reorders batched-matmul
   accumulation — ulp-level per step, see docs/design.md);
3. exports the family as an artifact *batch*
   (:meth:`~tensordiffeq_tpu.factory.SurrogateFactory.export_family`)
   and fleet-serves it in the same process via
   ``FleetRouter.register_family`` — asserting the served answers are
   BIT-IDENTICAL to each member's own direct engine, and that residual
   queries run on the embedded AOT programs with no f_model
   re-attached;
4. prints the factory's narrated telemetry trail (family loss
   quantiles, members-converged, aggregate family points/s).
"""

import os
import tempfile

import numpy as np

from _common import example_args, scaled

from tensordiffeq_tpu import grad

MIN_BUCKET, MAX_BUCKET = 64, 256


def f_model(u, x, t, th):
    """The family residual: Allen-Cahn with the diffusion coefficient θ
    as the family parameter."""
    u_xx = grad(grad(u, "x"), "x")
    u_t = grad(u, "t")
    uv = u(x, t)
    return u_t(x, t) - th * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv


def build_problem(n_f, nx, nt, seed=0):
    from tensordiffeq_tpu import IC, DomainND, periodicBC

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], nx)
    domain.add("t", [0.0, 1.0], nt)
    domain.generate_collocation_points(n_f, seed=seed)

    def func_ic(x):
        return x ** 2 * np.cos(np.pi * x)

    def deriv_model(u, x, t):
        return u(x, t), grad(u, "x")(x, t)

    bcs = [IC(domain, [func_ic], var=[["x"]]),
           periodicBC(domain, ["x"], [deriv_model])]
    return domain, bcs


def main():
    args = example_args("Allen-Cahn surrogate factory: vmapped family "
                        "training -> artifact batch -> fleet serving")
    import jax

    from tensordiffeq_tpu import (CollocationSolverND, SurrogateFactory,
                                  fleet, telemetry)

    n_members = 4 if args.quick else 8
    n_f = scaled(args, 2048, 256)
    nx, nt = (128, 32) if not args.quick else (64, 16)
    widths = [32, 32] if not args.quick else [16, 16]
    epochs = scaled(args, 1000, 60)
    thetas = [1e-4 * (0.5 + m / (n_members - 1)) for m in range(n_members)]
    lam0 = np.ones((n_f, 1), np.float32)
    sa_kw = dict(Adaptive_type=1,
                 dict_adaptive={"residual": [True], "BCs": [False, False]},
                 init_weights={"residual": [lam0], "BCs": [None, None]})

    run_dir = os.path.join(tempfile.mkdtemp(), "factory_run")
    logger = telemetry.RunLogger(run_dir, config={"example": "ac_factory",
                                                 "members": n_members})
    tele = telemetry.TrainingTelemetry(logger=logger)

    # -- 1. the family, one program ---------------------------------- #
    domain, bcs = build_problem(n_f, nx, nt)
    fac = SurrogateFactory([2, *widths, 1], f_model, domain, bcs,
                           thetas=thetas, seed=0, verbose=False, **sa_kw)
    print(f"[factory] family of {n_members} members "
          f"({fac.engine} engine), θ ∈ [{thetas[0]:.2e}, {thetas[-1]:.2e}]")
    fac.fit(tf_iter=epochs, chunk=min(100, epochs), telemetry=tele,
            converge_loss=1.0)
    losses = fac.member_losses()
    print(f"[factory] {epochs} epochs: member losses "
          f"{np.array2string(losses, precision=3)}")
    assert np.isfinite(losses).all(), "a member diverged"
    assert not fac.frozen_at

    # -- 2. matched-seed solo references ------------------------------ #
    # the documented family cross-check band (docs/design.md): per-step
    # math identical to the solo solver up to batched-matmul
    # accumulation order; over a short budget the trajectories track to
    # ~1e-3 relative.
    for m in (0, n_members - 1):
        d_m, bcs_m = build_problem(n_f, nx, nt)
        solo = CollocationSolverND(verbose=False, seed=m)
        solo.compile([2, *widths, 1],
                     lambda u, x, t, _t=thetas[m]: f_model(u, x, t, _t),
                     d_m, bcs_m, **sa_kw)
        solo.fit(tf_iter=epochs, chunk=min(100, epochs))
        hist_m = np.array([float(r["Total Loss"][m]) for r in fac.losses])
        hist_s = np.array([r["Total Loss"] for r in solo.losses])
        drift = float(np.max(np.abs(hist_m - hist_s)
                             / np.maximum(np.abs(hist_s), 1e-9)))
        print(f"[crosscheck] member {m} vs solo reference: "
              f"max rel loss drift {drift:.2e}")
        assert drift < 5e-2, (m, drift)

    # -- 3. artifact batch -> fleet ----------------------------------- #
    fam_dir = os.path.join(tempfile.mkdtemp(), "family")
    manifest = fac.export_family(fam_dir, min_bucket=MIN_BUCKET,
                                 max_bucket=MAX_BUCKET)
    print(f"[export] {len(manifest['members'])} member artifacts "
          f"-> {fam_dir}")
    router = fleet.FleetRouter(max_loaded=n_members)
    names = router.register_family(
        fam_dir, policy=fleet.TenantPolicy(min_bucket=MIN_BUCKET,
                                           max_bucket=MAX_BUCKET))
    rng = np.random.RandomState(0)
    Xq = np.stack([rng.uniform(-1, 1, 64),
                   rng.uniform(0, 1, 64)], -1).astype(np.float32)
    for m in (0, n_members - 1):
        served = np.asarray(router.query(names[m], Xq))
        direct = np.asarray(fac.member_surrogate(m).engine(
            min_bucket=MIN_BUCKET, max_bucket=MAX_BUCKET).u(Xq))
        assert np.array_equal(served, direct), m
        # residual through the embedded AOT program — no f_model needed
        res = np.asarray(router.query(names[m], Xq, kind="residual"))
        assert np.isfinite(res).all()
    print(f"[fleet] {len(names)} tenants served; member answers "
          "bit-identical to their direct engines, residual kind on AOT")

    # -- 4. the narrated trail ---------------------------------------- #
    logger.close()
    print(telemetry.report(run_dir))


if __name__ == "__main__":
    main()
