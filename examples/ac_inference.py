"""Allen-Cahn discovery-model inference — load and evaluate
(reference ``examples/AC-inference.py:18-26``: rebuild ``f_model`` with
tunable ``var``, point a DiscoveryModel at the Raissi 512x201 grid, and
evaluate the discovered dynamics; its per-optimizer customization hook is
the ``lr_weights=`` knob here).

The flow this script demonstrates is the half the training example leaves
out: a model discovered (and checkpointed) earlier is restored into a
FRESH process-state and interrogated —

* the recovered coefficients (c1, c2),
* the residual of the *learned* PDE over the full grid (``predict_f``),
* the solution fit (rel-L2 vs the spectral solution),
* the trained SA collocation-weight field (``plot_weights``).

Run after ``ac_discovery.py`` with ``--ckpt <dir>`` to load its
checkpoint, or standalone (it trains a short discovery run first, saves
it, and then restores it — the restore path is always exercised).
"""

import os
import tempfile

import numpy as np

from _common import example_args, scaled

from tensordiffeq_tpu import DiscoveryModel, find_L2_error, grad, plotting
from tensordiffeq_tpu.exact import allen_cahn_solution


def f_model(u, var, x, t):
    c1, c2 = var
    u_xx = grad(grad(u, "x"), "x")
    uv = u(x, t)
    return grad(u, "t")(x, t) - c1 * u_xx(x, t) + c2 * uv ** 3 - c2 * uv


def build(X, u_star, widths, col_weights):
    model = DiscoveryModel()
    model.compile([2, *widths, 1], f_model,
                  [X[:, 0:1], X[:, 1:2]], u_star, var=[0.0, 0.0],
                  col_weights=col_weights, varnames=["x", "t"],
                  lr_weights=0.005, verbose=False)
    return model


def main():
    args = example_args("Allen-Cahn discovery inference", flags=("no-sa",),
                        ckpt=("", "checkpoint dir from ac_discovery.py — "
                              "pass the SAME --quick/--no-sa flags as the "
                              "training run so the model shapes match"))

    x, t, usol = allen_cahn_solution()
    if args.quick:
        x, t, usol = x[::8], t[::8], usol[::8, ::8]
    X = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)
    widths = [128] * 4 if not args.quick else [32] * 2
    rng = np.random.RandomState(0)
    col_weights = None if args.no_sa else rng.rand(X.shape[0], 1)

    ckpt = args.ckpt
    if not ckpt:
        # no checkpoint supplied: produce one so the restore path below is
        # real (a short run; ac_discovery.py is the full-budget trainer)
        print("[inference] no --ckpt; training a short discovery run first")
        trainer = build(X, u_star, widths, col_weights)
        trainer.fit(tf_iter=scaled(args, 2_000, 100))
        ckpt = os.path.join(tempfile.mkdtemp(), "ac_inference_ckpt")
        trainer.save_checkpoint(ckpt)
        del trainer

    # ---- the inference flow: fresh model, restored state ---- #
    model = build(X, u_star, widths, col_weights)
    try:
        model.restore_checkpoint(ckpt)
    except Exception as e:
        raise SystemExit(
            f"failed to restore {ckpt}: {type(e).__name__}: {e}\n"
            "The inference model must be built EXACTLY like the training "
            "run — re-run with the same --quick and --no-sa flags you gave "
            "ac_discovery.py (net widths, grid size, and SA col_weights "
            "all shape the checkpoint).") from e

    c1, c2 = (float(v) for v in model.vars)
    print(f"discovered: c1 = {c1:.6f} (true 0.0001), "
          f"c2 = {c2:.4f} (true 5.0)")

    f_pred = model.predict_f(X)
    print(f"learned-PDE residual over the grid: mean|f| = "
          f"{np.abs(f_pred).mean():.3e}, max|f| = {np.abs(f_pred).max():.3e}")

    u_pred = model.predict(X)
    print(f"solution fit: rel-L2 = {find_L2_error(u_pred, u_star):.3e}")

    if args.plot and not args.no_sa:
        os.makedirs(args.plot, exist_ok=True)
        plotting.plot_weights(
            model, scale=10.0,
            save_path=os.path.join(args.plot, "ac_inference_weights.png"))
        print(f"[inference] weight plot -> {args.plot}")
    return model


if __name__ == "__main__":
    main()
