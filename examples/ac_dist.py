"""Allen-Cahn distributed data-parallel training — the scale config
(reference ``examples/AC-dist-new.py``: N_f=500,000 collocation points,
Adam-only, multi-GPU ``MirroredStrategy``).

TPU-native version: ``dist=True`` shards the 500k-point batch (and nothing
else — params replicate) across every local device of a 1-D
``jax.sharding.Mesh``; XLA inserts the ICI all-reduces.  Unlike the
reference, L-BFGS refinement also works distributed (the reference disables
it, ``fit.py:222-223``), and so do SA weights (``--sa``), which shard
row-aligned with their points.

Run on CPU with a virtual mesh for a functional check:
``XLA_FLAGS=--xla_force_host_platform_device_count=8 python ac_dist.py --quick``
"""

import numpy as np

from _common import example_args, scaled

from ac_baseline import build_problem, evaluate

import jax
from tensordiffeq_tpu import CollocationSolverND


def main():
    args = example_args("Allen-Cahn distributed data-parallel", flags=("sa",))
    use_sa = args.sa

    n_f = scaled(args, 500_000, 4_096)
    nx = 512 if not args.quick else 64
    domain, bcs, f_model = build_problem(n_f, nx=nx,
                                         nt=201 if not args.quick else 21)
    widths = [128] * 4 if not args.quick else [32] * 2

    kwargs = {}
    if use_sa:
        rng = np.random.RandomState(0)
        kwargs = dict(Adaptive_type=1,
                      dict_adaptive={"residual": [True], "BCs": [True, False]},
                      init_weights={"residual": [rng.rand(n_f, 1)],
                                    "BCs": [100.0 * rng.rand(nx, 1), None]})

    print(f"devices: {jax.devices()}")
    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs, dist=True, **kwargs)
    # reference runs 1001 Adam iters x 2 passes, no L-BFGS; we add a short
    # L-BFGS tail since the distributed path supports it
    solver.fit(tf_iter=scaled(args, 1_001, 60))
    solver.fit(tf_iter=scaled(args, 1_001, 60),
               newton_iter=scaled(args, 1_000, 30))
    return evaluate(solver, args, "ac_dist")


if __name__ == "__main__":
    main()
