"""Steady-state Poisson equation with function-valued Dirichlet BCs
(reference ``examples/steady-state-poisson.py``).

u_xx + u_yy = -sin(pi x) sin(pi y) on [0,1]^2; exact solution
sin(pi x) sin(pi y) / (2 pi^2).  Exercises ``FunctionDirichletBC`` (the
face values happen to be zero at the unit-square boundary, as in the
reference, but are computed from the user functions).
"""

import numpy as np

from _common import example_args, scaled

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import (CollocationSolverND, DomainND, dirichletBC,
                              FunctionDirichletBC, grad)


def main():
    args = example_args("Poisson steady state")

    domain = DomainND(["x", "y"])
    domain.add("x", [0.0, 1.0], 11)
    domain.add("y", [0.0, 1.0], 11)
    domain.generate_collocation_points(scaled(args, 100, 100), seed=0)

    def func_upper_x(y):
        return -np.sin(np.pi * y) * np.sin(np.pi)

    def func_upper_y(x):
        return -np.sin(np.pi * x) * np.sin(np.pi)

    bcs = [FunctionDirichletBC(domain, fun=[func_upper_x], var="x",
                               target="upper", func_inputs=[["y"]],
                               n_values=10),
           dirichletBC(domain, val=0.0, var="x", target="lower"),
           FunctionDirichletBC(domain, fun=[func_upper_y], var="y",
                               target="upper", func_inputs=[["x"]],
                               n_values=10),
           dirichletBC(domain, val=0.0, var="y", target="lower")]

    def f_model(u, x, y):
        import jax.numpy as jnp
        u_xx = grad(grad(u, "x"), "x")(x, y)
        u_yy = grad(grad(u, "y"), "y")(x, y)
        forcing = -jnp.sin(np.pi * x) * jnp.sin(np.pi * y)
        return u_xx + u_yy - forcing

    solver = CollocationSolverND()
    solver.compile([2, 16, 16, 1], f_model, domain, bcs)
    solver.fit(tf_iter=scaled(args, 4_000, 200))

    n = 101
    xv, yv = np.meshgrid(np.linspace(0, 1, n), np.linspace(0, 1, n))
    exact = np.sin(np.pi * xv) * np.sin(np.pi * yv) / (2 * np.pi ** 2)
    Xg = np.hstack([xv.reshape(-1, 1), yv.reshape(-1, 1)])
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = tdq.find_L2_error(u_pred, exact.reshape(-1, 1))
    print(f"Error u: {err:e}")
    return err


if __name__ == "__main__":
    main()
