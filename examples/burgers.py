"""Forward viscous Burgers PINN (reference ``examples/burgers-new.py``).

u_t + u u_x = (0.01/pi) u_xx on x in [-1,1], t in [0,1];
u(x,0) = -sin(pi x), u(+-1,t) = 0.  N_f=10k, 2-20x8-1 tanh MLP,
10k Adam + 10k L-BFGS; validates rel-L2 against the Cole-Hopf solution.

``--resample N`` turns on residual-importance collocation resampling
(beyond-reference, ops/resampling.py): redraw the N_f points every N Adam
epochs toward where |f| is large — the shock line here.
"""

import numpy as np

from _common import example_args, scaled, fit_resumable

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC, dirichletBC,
                              grad)
from tensordiffeq_tpu.exact import burgers_solution


def main():
    args = example_args("Burgers shock forward PINN",
                        resample=(0, "redraw collocation points every N "
                                     "Adam epochs (0 = reference fixed set)"))

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(scaled(args, 10_000, 1_000), seed=0)

    bcs = [IC(domain, [lambda x: -np.sin(np.pi * x)], var=[["x"]]),
           dirichletBC(domain, val=0.0, var="x", target="upper"),
           dirichletBC(domain, val=0.0, var="x", target="lower")]

    def f_model(u, x, t):
        u_x, u_t = grad(u, "x"), grad(u, "t")
        u_xx = grad(u_x, "x")
        return u_t(x, t) + u(x, t) * u_x(x, t) - (0.01 / np.pi) * u_xx(x, t)

    widths = [20] * 8 if not args.quick else [20] * 4
    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs)
    fit_resumable(solver, quick=args.quick, tf_iter=scaled(args, 10_000, 200),
               newton_iter=scaled(args, 10_000, 100),
               resample_every=args.resample)

    x, t, usol = burgers_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = tdq.find_L2_error(u_pred, usol.reshape(-1, 1))
    print(f"Error u: {err:e}")

    if args.plot:
        tdq.plotting.plot_solution_domain1D(
            solver, [x, t], ub=[1.0, 1.0], lb=[-1.0, 0.0], Exact_u=usol,
            save_path=f"{args.plot}/burgers.png", best_model=True)
    return err


if __name__ == "__main__":
    main()
