"""Forward viscous Burgers PINN (reference ``examples/burgers-new.py``).

u_t + u u_x = (0.01/pi) u_xx on x in [-1,1], t in [0,1];
u(x,0) = -sin(pi x), u(+-1,t) = 0.  Validates rel-L2 against the
Cole-Hopf solution.

The problem declaration (domain, BCs, residual, sizes, budgets, gate)
lives in the zoo registry (``tensordiffeq_tpu.zoo``, entry ``burgers``)
— this script is a thin CLI wrapper that resolves its config from there,
so the example and the scorecard can never drift apart.

``--resample N`` turns on residual-importance collocation resampling
(beyond-reference, ops/resampling.py): redraw the N_f points every N Adam
epochs toward where |f| is large — the shock line here.
"""

from _common import example_args, fit_resumable, zoo_spec

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import zoo
from tensordiffeq_tpu.exact import burgers_solution

ENTRY = zoo.get("burgers")


def main():
    args = example_args("Burgers shock forward PINN",
                        resample=(0, "redraw collocation points every N "
                                     "Adam epochs (0 = reference fixed set)"))
    spec = zoo_spec(ENTRY, args.quick)

    solver = zoo.build_solver(ENTRY, spec=spec)
    fit_resumable(solver, quick=args.quick, tf_iter=spec.budget.adam,
                  newton_iter=spec.budget.lbfgs,
                  resample_every=args.resample)

    ref = ENTRY.reference(spec)
    u_pred, _ = solver.predict(ref.X, best_model=True)
    err = tdq.find_L2_error(ref.compare(u_pred), ref.u)
    print(f"Error u: {err:e}")

    if args.plot:
        x, t, usol = burgers_solution()
        tdq.plotting.plot_solution_domain1D(
            solver, [x, t], ub=[1.0, 1.0], lb=[-1.0, 0.0], Exact_u=usol,
            save_path=f"{args.plot}/burgers.png", best_model=True)
    return err


if __name__ == "__main__":
    main()
