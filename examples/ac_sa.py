"""Allen-Cahn Self-Adaptive PINN — the flagship config
(reference ``examples/AC-SA.py``; SA-PINN, McClenny et al. arXiv:2009.04544).

Same PDE as ``ac_baseline.py`` plus per-point minimax loss weights:
lambda_residual ~ U[0,1] over the 50k collocation points, lambda_IC ~
100*U[0,1] over the 512 IC points, trained by gradient ascent while the
network descends.  (The reference script passes the stale string
``Adaptive_type='self-adaptive'`` which its own compile() rejects —
SURVEY §2.4.7; the working encoding is Adaptive_type=1.)
"""

from _common import example_args, scaled, fit_resumable

from ac_baseline import build_sa_solver, evaluate

import tensordiffeq_tpu as tdq


def main():
    args = example_args("Allen-Cahn Self-Adaptive PINN",
                        flags=("periodic-net",))
    n_f = scaled(args, 50_000, 2_000)
    nx = 512 if not args.quick else 64
    widths = [128] * 4 if not args.quick else [32] * 2

    # --periodic-net: beyond-reference exactly-periodic embedding ansatz
    # (networks.PeriodicMLP) — the x-periodicity the reference enforces
    # softly is built into the network, at the cost of the generic
    # (non-fused) residual engine.
    solver = build_sa_solver(n_f, nx, 201 if not args.quick else 21,
                             widths, periodic=args.periodic_net,
                             verbose=True)
    fit_resumable(solver, quick=args.quick, tf_iter=scaled(args, 10_000, 200),
               newton_iter=scaled(args, 10_000, 100))
    err = evaluate(solver, args, "ac_sa")
    if args.plot:
        tdq.plotting.plot_weights(solver, save_path=f"{args.plot}/ac_sa_weights.png")
    return err


if __name__ == "__main__":
    main()
