"""Allen-Cahn Self-Adaptive PINN — the flagship config
(reference ``examples/AC-SA.py``; SA-PINN, McClenny et al. arXiv:2009.04544).

Same PDE as ``ac_baseline.py`` plus per-point minimax loss weights:
lambda_residual ~ U[0,1] over the 50k collocation points, lambda_IC ~
100*U[0,1] over the 512 IC points, trained by gradient ascent while the
network descends.  (The reference script passes the stale string
``Adaptive_type='self-adaptive'`` which its own compile() rejects —
SURVEY §2.4.7; the working encoding is Adaptive_type=1.)
"""

import numpy as np

from _common import example_args, scaled, fit_resumable

from ac_baseline import build_problem, evaluate

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import CollocationSolverND


def main():
    args = example_args("Allen-Cahn Self-Adaptive PINN",
                        flags=("periodic-net",))
    n_f = scaled(args, 50_000, 2_000)
    nx = 512 if not args.quick else 64
    domain, bcs, f_model = build_problem(n_f, nx=nx,
                                         nt=201 if not args.quick else 21)
    widths = [128] * 4 if not args.quick else [32] * 2

    rng = np.random.RandomState(0)
    dict_adaptive = {"residual": [True], "BCs": [True, False]}
    init_weights = {"residual": [rng.rand(n_f, 1)],
                    "BCs": [100.0 * rng.rand(nx, 1), None]}

    # --periodic-net: beyond-reference exactly-periodic embedding ansatz
    # (networks.PeriodicMLP) — the x-periodicity the reference enforces
    # softly is built into the network, at the cost of the generic
    # (non-fused) residual engine.
    network = (tdq.periodic_net([2, *widths, 1], domain, ["x"])
               if args.periodic_net else None)

    solver = CollocationSolverND()
    solver.compile([2, *widths, 1], f_model, domain, bcs, Adaptive_type=1,
                   dict_adaptive=dict_adaptive, init_weights=init_weights,
                   network=network)
    fit_resumable(solver, quick=args.quick, tf_iter=scaled(args, 10_000, 200),
               newton_iter=scaled(args, 10_000, 100))
    err = evaluate(solver, args, "ac_sa")
    if args.plot:
        tdq.plotting.plot_weights(solver, save_path=f"{args.plot}/ac_sa_weights.png")
    return err


if __name__ == "__main__":
    main()
