"""Allen-Cahn Self-Adaptive PINN — the flagship config
(reference ``examples/AC-SA.py``; SA-PINN, McClenny et al. arXiv:2009.04544).

Same PDE as ``ac_baseline.py`` plus per-point minimax loss weights:
lambda_residual ~ U[0,1] over the 50k collocation points, lambda_IC ~
100*U[0,1] over the 512 IC points, trained by gradient ascent while the
network descends.  (The reference script passes the stale string
``Adaptive_type='self-adaptive'`` which its own compile() rejects —
SURVEY §2.4.7; the working encoding is Adaptive_type=1.)
"""

from _common import example_args, fit_resumable, zoo_spec

from ac_baseline import build_sa_solver, evaluate

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import zoo

ENTRY = zoo.get("allen-cahn-sa")


def main():
    args = example_args("Allen-Cahn Self-Adaptive PINN",
                        flags=("periodic-net",))
    # one source of truth: sizes/budgets come from the zoo entry; the
    # SA compile config is inside its builder (ac_baseline wraps it)
    spec = zoo_spec(ENTRY, args.quick)
    nx, nt = spec.grid

    # --periodic-net: beyond-reference exactly-periodic embedding ansatz
    # (networks.PeriodicMLP) — the x-periodicity the reference enforces
    # softly is built into the network, at the cost of the generic
    # (non-fused) residual engine.
    solver = build_sa_solver(spec.n_f, nx, nt, list(spec.widths),
                             periodic=args.periodic_net, verbose=True)
    fit_resumable(solver, quick=args.quick, tf_iter=spec.budget.adam,
                  newton_iter=spec.budget.lbfgs)
    err = evaluate(solver, args, "ac_sa")
    if args.plot:
        tdq.plotting.plot_weights(solver, save_path=f"{args.plot}/ac_sa_weights.png")
    return err


if __name__ == "__main__":
    main()
