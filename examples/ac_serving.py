"""Allen-Cahn serving: train -> export -> restore in a FRESH process ->
batched grid + derivative queries.

The half every training example leaves out: after ``fit`` the solver is a
training object (optimizer moments, SA λ, collocation set), but what a
deployment wants is the *surrogate* — net + params + residual closure and
nothing else.  This script

1. trains a short SA run (``ac_baseline.build_sa_solver``, the flagship
   config) and exports it: ``solver.export_surrogate().save(dir)``;
2. re-invokes itself as a subprocess (``--serve <dir>``) so the restore
   genuinely happens in a fresh process with no solver, no domain, and no
   training state in scope;
3. in that process, serves batched queries through the
   :class:`~tensordiffeq_tpu.serving.InferenceEngine`: ``u`` over the full
   Raissi grid, first/second derivatives, the PDE residual — and closes
   the loop by recombining the derivative queries into the residual by
   hand, which must match ``engine.residual`` to float tolerance;
4. coalesces a burst of small point queries through the
   :class:`~tensordiffeq_tpu.serving.RequestBatcher` and prints its
   QPS / latency-percentile stats.
"""

import os
import subprocess
import sys
import tempfile

import numpy as np

from _common import example_args, scaled

from tensordiffeq_tpu import grad


def f_model(u, x, t):
    u_xx = grad(grad(u, "x"), "x")
    u_t = grad(u, "t")
    uv = u(x, t)
    return u_t(x, t) - 0.0001 * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv


def serve(artifact: str, quick: bool):
    """The fresh-process half: restore the artifact and query it batched.
    Nothing here touches a solver, a domain, or any training state."""
    from tensordiffeq_tpu import find_L2_error
    from tensordiffeq_tpu.exact import allen_cahn_solution
    from tensordiffeq_tpu.serving import RequestBatcher, Surrogate

    sur = Surrogate.load(artifact, f_model=f_model)
    engine = sur.engine(min_bucket=64, max_bucket=4096 if quick else 1 << 17)
    print(f"[serve] restored {artifact}: vars={sur.varnames}, "
          f"layers={sur.layer_sizes}, buckets={engine.bucket_sizes}")

    x, t, usol = allen_cahn_solution()
    if quick:
        x, t, usol = x[::8], t[::8], usol[::8, ::8]
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)

    # -- batched grid evaluation ---------------------------------------- #
    u = engine.u(Xg)
    print(f"[serve] u over the {usol.shape} grid: rel-L2 = "
          f"{find_L2_error(u, usol.reshape(-1, 1)):.3e} "
          f"(short training run — fit quality is ac_sa.py's job)")

    # -- derivative queries, recombined into the residual by hand ------- #
    u_t = engine.derivative(Xg, "t")
    u_xx = engine.derivative(Xg, "x", order=2)
    f = engine.residual(Xg)
    uv = u[:, 0]
    by_hand = u_t - 0.0001 * u_xx + 5.0 * uv ** 3 - 5.0 * uv
    gap = float(np.max(np.abs(by_hand - f)))
    print(f"[serve] residual: mean|f| = {np.abs(f).mean():.3e}; "
          f"recombined from derivative queries to within {gap:.2e}")
    assert gap < 1e-4, "derivative queries disagree with engine.residual"

    # -- coalesced small queries ---------------------------------------- #
    rng = np.random.RandomState(0)
    batcher = RequestBatcher(engine, max_batch=512, max_latency_s=0.005)
    handles = [batcher.submit(
        np.stack([rng.uniform(-1, 1, n), rng.uniform(0, 1, n)], -1))
        for n in rng.randint(1, 17, size=100)]
    batcher.flush()
    assert all(h.done for h in handles)
    s = batcher.stats()
    print(f"[serve] batcher: {s['requests']} requests -> {s['batches']} "
          f"device batches, {s['qps']:.0f} QPS, "
          f"p99 = {s['latency_s']['p99'] * 1e3:.1f} ms")
    print(f"[serve] compile cache: {engine.compile_cache_size} programs "
          f"(bound: kinds x {engine.n_buckets} buckets)")


def main():
    args = example_args(
        "Allen-Cahn serving: train -> export -> fresh-process restore",
        serve=("", "internal: restore and serve this artifact dir "
                   "(the fresh-process half; invoked automatically)"))
    if args.serve:
        return serve(args.serve, args.quick)

    from ac_baseline import build_sa_solver

    n_f = scaled(args, 50_000, 2_000)
    nx, nt = (512, 201) if not args.quick else (64, 21)
    widths = [128] * 4 if not args.quick else [32] * 2
    solver = build_sa_solver(n_f, nx, nt, widths, seed=0)
    solver.fit(tf_iter=scaled(args, 2_000, 100))

    artifact = os.path.join(tempfile.mkdtemp(), "ac_surrogate")
    solver.export_surrogate().save(artifact)
    print(f"[train] exported surrogate -> {artifact}")

    # the restore must survive a genuinely fresh process: no solver, no
    # domain, no jitted state — only the artifact and the f_model source
    cmd = [sys.executable, os.path.abspath(__file__), "--serve", artifact]
    if args.quick:
        cmd.append("--quick")
    return subprocess.run(cmd, check=True, cwd=os.path.dirname(
        os.path.abspath(__file__))).returncode


if __name__ == "__main__":
    sys.exit(main() or 0)
