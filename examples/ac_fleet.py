"""Allen-Cahn fleet serving: train TWO surrogates -> export AOT fleet
artifacts -> serve both, multi-tenant, in a FRESH process.

The fleet half every single-surrogate example leaves out: a deployment
hosts many trained surrogates at once, and a fresh replica must answer
its first query without a jit storm.  This script

1. trains two short SA runs (different seeds — two tenants of the same
   PDE family) and exports each with
   :func:`tensordiffeq_tpu.fleet.export_fleet_artifact`: the artifact
   carries the pad-to-bucket ladder spec plus ``jax.export``-serialized
   compiled programs for every (kind, bucket) rung;
2. re-invokes itself as a subprocess (``--serve dirA,dirB``) so the
   fleet restore genuinely happens in a fresh process;
3. in that process, a :class:`~tensordiffeq_tpu.fleet.FleetRouter`
   hot-loads both tenants (tenant "b" deliberately gets NO f_model —
   its residual queries run entirely on the AOT programs), proves the
   warm start compiled ZERO programs at request time via the engine's
   per-bucket compile counters, serves mixed u/residual traffic through
   per-tenant batchers behind admission control, sheds a deliberate
   burst over tenant "b"'s rate limit as structured
   :class:`~tensordiffeq_tpu.fleet.AdmissionRejected`, and closes the
   loop by checking fleet answers bit-identical against a direct
   :class:`~tensordiffeq_tpu.serving.InferenceEngine`;
4. prints the run's narrated telemetry report — the FLEET / WARM START /
   ADMISSION trail an operator would read after the fact.
"""

import os
import subprocess
import sys
import tempfile
import time

import numpy as np

from _common import example_args, scaled

from tensordiffeq_tpu import grad

MIN_BUCKET, MAX_BUCKET = 64, 1024


def f_model(u, x, t):
    u_xx = grad(grad(u, "x"), "x")
    u_t = grad(u, "t")
    uv = u(x, t)
    return u_t(x, t) - 0.0001 * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv


def serve(artifacts: str, quick: bool):
    """The fresh-process half: fleet-serve the exported artifacts."""
    from tensordiffeq_tpu import fleet, telemetry
    from tensordiffeq_tpu.serving import Surrogate

    art_a, art_b = artifacts.split(",")
    run_dir = os.path.join(tempfile.mkdtemp(), "fleet_run")
    with telemetry.RunLogger(run_dir, config={"example": "ac_fleet"}), \
            telemetry.Tracer():
        router = fleet.FleetRouter(max_loaded=2)
        policy = fleet.TenantPolicy(min_bucket=MIN_BUCKET,
                                    max_bucket=MAX_BUCKET,
                                    max_batch=512, max_latency_s=0.005)
        router.register("a", art_a, f_model=f_model, policy=policy)
        # tenant "b" gets NO f_model: its residual queries must run
        # entirely on the artifact's AOT programs
        router.register("b", art_b, policy=fleet.TenantPolicy(
            min_bucket=MIN_BUCKET, max_bucket=MAX_BUCKET, max_batch=512,
            max_latency_s=0.005, rate_qps=5.0, burst=3.0, priority=0))

        # -- warm start: zero compiles at request time ------------------ #
        reg = telemetry.default_registry()

        def compiles():
            return sum(v for k, v in reg.as_dict()["counters"].items()
                       if k.startswith("serving.engine.compiles"))

        lt = router.load("a")
        print(f"[fleet] loaded tenant a: {lt.warm['aot']} AOT + "
              f"{lt.warm['jit']} jit programs in {lt.warm['wall_s']:.2f}s")
        before = compiles()
        rng = np.random.RandomState(0)

        def draw(n):
            return np.stack([rng.uniform(-1, 1, n),
                             rng.uniform(0, 1, n)], -1).astype(np.float32)

        Xq = draw(200)
        u_a = router.query("a", Xq)
        assert compiles() - before == 0, \
            "warm-started tenant compiled at request time"
        print("[fleet] first query served with 0 request-time compiles")

        # -- the query left a COMPLETE span tree in events.jsonl -------- #
        spans = telemetry.tracing.read_spans(run_dir)
        trees = telemetry.tracing.span_tree(spans)
        [req] = [r for group in trees.values() for r in group
                 if r["name"] == "fleet.request"]

        def names(node, acc):
            acc.add(node["name"])
            for c in node["children"]:
                names(c, acc)
            return acc

        got = names(req, set())
        for expected in ("fleet.request", "fleet.submit",
                         "fleet.admission", "fleet.load",
                         "serving.batcher.enqueue",
                         "serving.batcher.flush", "serving.engine.run",
                         "serving.engine.dispatch",
                         "serving.engine.device"):
            assert expected in got, \
                f"span {expected!r} missing from the request trace {got}"
        print(f"[fleet] request trace {req['trace']}: "
              f"{len(got)} span kinds, admission -> engine dispatch, "
              f"{req['dur_s'] * 1e3:.1f}ms end to end")

        # -- mixed multi-tenant traffic --------------------------------- #
        n_req = 40 if quick else 400
        rejected = 0
        for i in range(n_req):
            tenant = "ab"[i % 2]
            kind = "residual" if i % 3 == 0 else "u"
            try:
                router.submit(tenant, draw(int(rng.randint(1, 17))),
                              kind=kind)
            except fleet.AdmissionRejected as e:
                rejected += 1
                assert e.tenant == "b" and e.reason == "rate_limit"
            router.poll()
        router.flush()
        assert rejected > 0, "tenant b's rate limit never shed"
        sig = router.autoscale_signals()
        print(f"[fleet] {n_req} submits over 2 tenants, {rejected} shed "
              f"(tenant b rate limit); cache hit rate "
              f"{sig['cache_hit_rate']:.2f}")
        for t, d in sorted(sig["tenants"].items()):
            print(f"[fleet]   tenant {t}: qps={d['qps']:.0f} "
                  f"p99={1e3 * (d['latency_p99_s'] or 0):.1f}ms")

        # -- bit-identity + AOT residual without f_model ---------------- #
        direct = Surrogate.load(art_a, f_model=f_model).engine(
            min_bucket=MIN_BUCKET, max_bucket=MAX_BUCKET)
        assert np.array_equal(u_a, direct.u(Xq)), \
            "fleet u differs from the direct engine"
        # tenant b's token bucket may still be drained by the traffic
        # loop — wait out the structured backpressure hint (bounded)
        for _ in range(40):
            try:
                f_b = router.query("b", Xq, kind="residual")  # no f_model
                break
            except fleet.AdmissionRejected as e:
                time.sleep(max(e.retry_after_s, 0.05))
        else:
            raise AssertionError("tenant b's rate budget never refilled")
        direct_b = Surrogate.load(art_b, f_model=f_model).engine(
            min_bucket=MIN_BUCKET, max_bucket=MAX_BUCKET)
        assert np.array_equal(f_b, direct_b.residual(Xq)), \
            "AOT residual differs from the direct engine"
        print("[fleet] fleet answers bit-identical to direct engines "
              "(tenant b's residual served with NO f_model, AOT only)")

    print(telemetry.report(run_dir))


def main():
    args = example_args(
        "Allen-Cahn fleet: two surrogates -> AOT export -> fresh-process "
        "multi-tenant serving",
        serve=("", "internal: fleet-serve these comma-separated artifact "
                   "dirs (the fresh-process half; invoked automatically)"))
    if args.serve:
        return serve(args.serve, args.quick)

    from ac_baseline import build_sa_solver

    from tensordiffeq_tpu import fleet

    n_f = scaled(args, 20_000, 1_000)
    nx, nt = (256, 101) if not args.quick else (64, 21)
    widths = [64] * 3 if not args.quick else [16] * 2
    root = tempfile.mkdtemp()
    artifacts = []
    for name, seed in (("a", 0), ("b", 1)):
        solver = build_sa_solver(n_f, nx, nt, widths, seed=seed)
        solver.fit(tf_iter=scaled(args, 1_000, 50))
        art = os.path.join(root, f"ac_{name}")
        fleet.export_fleet_artifact(
            solver.export_surrogate(), art,
            min_bucket=MIN_BUCKET, max_bucket=MAX_BUCKET)
        artifacts.append(art)
        print(f"[train] exported fleet artifact {name} -> {art}")

    # the restore must survive a genuinely fresh process: no solvers, no
    # domains, no jitted state — only the artifacts (and f_model for "a")
    cmd = [sys.executable, os.path.abspath(__file__),
           "--serve", ",".join(artifacts)]
    if args.quick:
        cmd.append("--quick")
    return subprocess.run(cmd, check=True, cwd=os.path.dirname(
        os.path.abspath(__file__))).returncode


if __name__ == "__main__":
    main()  # plain call: test_examples runs this in-process via runpy
