"""2-D + time Burgers-type equation (reference ``examples/testing.py``).

u_t + u u_x = nu u_xx on (x, y) in [-1,1]^2, t in [0,1], with
u(x,y,0) = -sin(pi x) - sin(pi y) and periodic BCs (value + first/second
derivatives) in both spatial variables — exercises the 3-input path, the
multi-variable periodic BC, and higher-derivative matching.
"""

import numpy as np

from _common import example_args, scaled, fit_resumable

from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC, grad,
                              periodicBC)


def main():
    args = example_args(
        "2D+time Burgers-type PDE",
        nf=(0, "override N_f (0 = config default)"),
        adam=(0, "override Adam iters (0 = config default)"),
        newton=(0, "override L-BFGS iters (0 = config default)"),
        width=(0, "override hidden width (0 = config default)"))

    domain = DomainND(["x", "y", "t"], time_var="t")
    fid = 256 if not args.quick else 24
    domain.add("x", [-1.0, 1.0], fid)
    domain.add("y", [-1.0, 1.0], fid)
    domain.add("t", [0.0, 1.0], 100 if not args.quick else 11)
    domain.generate_collocation_points(args.nf or scaled(args, 20_000, 1_500),
                                       seed=0)

    def func_ic_xy(x, y):
        return -np.sin(np.pi * x) - np.sin(np.pi * y)

    def deriv_model(u, x, y, t):
        u_x, u_y = grad(u, "x"), grad(u, "y")
        return (u(x, y, t), u_x(x, y, t), u_y(x, y, t),
                grad(u_x, "x")(x, y, t), grad(u_y, "y")(x, y, t),
                grad(u_x, "y")(x, y, t), grad(u_y, "x")(x, y, t))

    bcs = [IC(domain, [func_ic_xy], var=[["x", "y"]]),
           periodicBC(domain, ["x", "y"], [deriv_model, deriv_model])]

    def f_model(u, x, y, t):
        u_x = grad(u, "x")
        u_xx = grad(u_x, "x")
        u_t = grad(u, "t")
        return (u_t(x, y, t) + u(x, y, t) * u_x(x, y, t)
                - (0.05 / np.pi) * u_xx(x, y, t))

    w = args.width or (128 if not args.quick else 24)
    widths = [w] * (4 if not args.quick else 2)
    solver = CollocationSolverND()
    solver.compile([3, *widths, 1], f_model, domain, bcs)
    fit_resumable(solver, quick=args.quick, tf_iter=args.adam or scaled(args, 1_000, 100),
               newton_iter=args.newton or scaled(args, 1_000, 50))
    print(f"final loss: {solver.losses[-1]['Total Loss']:.4e}")
    return solver


if __name__ == "__main__":
    main()
